"""Elastic resharding: restore any checkpoint onto any mesh, survive
preemption live (autodist_tpu/elastic/).

Goldens follow the repo's trajectory contract: train k steps on mesh
A, reshard to mesh B (dp/pp/tp changes, ZeRO-3 flat shards, the
vocab-parallel V % tp != 0 pad edge, bf16_ef compressor state),
continue k steps — the reshard itself is BIT-exact (same logical
state), and the continued trajectory matches never having switched to
the same tolerance the repo's cross-strategy parity goldens use.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, PartitionedPS, PS
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.elastic import (ElasticController, ReshardError,
                                  apply_ops, invert_ops, plan_reshard,
                                  reshard_state, shard_budget)
from autodist_tpu.elastic.reshard import build_convert_fn

from tests.unit.test_end_to_end import (make_batch, make_trainable,
                                        single_device_reference)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_cli_trainable():
    """Factory the reshard_ckpt CLI test names via --trainable."""
    return make_trainable(optimizer=optax.adam(1e-2))


def _momentum():
    return optax.sgd(0.1, momentum=0.9)


def assert_trees_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


def assert_trees_close(a, b, rtol=2e-5, atol=2e-6):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


# --------------------------------------------------------------------------- #
# Recipe ops / dtype plumbing
# --------------------------------------------------------------------------- #
def test_parse_dtype_rebuilds_exact_jnp_dtypes():
    from autodist_tpu.checkpoint.export import parse_dtype

    assert parse_dtype("bfloat16") == jnp.bfloat16
    assert parse_dtype("float32") == np.float32
    assert parse_dtype(np.dtype("int32")) == np.int32
    with pytest.raises(ValueError, match="wavelet16"):
        parse_dtype("wavelet16")


def test_recipe_ops_invert_roundtrip():
    """invert(ops) reconstructs the stored form exactly when padding
    lanes are zero (the repo-wide storage invariant)."""
    from autodist_tpu.kernel.lowering import (_op_flat_slice, _op_index0,
                                              _op_reshape, _op_slice)

    stored = np.zeros((6, 8), np.float32)
    stored[:6, :5] = np.arange(30, dtype=np.float32).reshape(6, 5)
    stored[5, 3:] = 0.0   # every lane a recipe op cuts is zero padding
    stored[5, :3] = 0.0
    perm = [3, 1, 5, 0, 2, 4]
    permuted = stored[perm]
    ops = [_op_slice((6, 8), (6, 5)),
           _op_index0((6, 5), np.argsort(perm)),
           _op_reshape((6, 5), (30,)),
           _op_flat_slice((30,), 25)]
    logical = apply_ops(permuted, ops, np)
    assert logical.shape == (25,)
    back = apply_ops(logical, invert_ops(ops), np)
    np.testing.assert_array_equal(back, permuted)


# --------------------------------------------------------------------------- #
# Collective family: dp shrink/grow with optimizer state
# --------------------------------------------------------------------------- #
def test_shrink_8_to_4_adam_state_survives(tmp_path):
    """AllReduce on 8 devices -> PS on 4: the elastic restore carries
    the Adam moments, so the continued trajectory matches the
    single-device reference (a fresh optimizer would diverge)."""
    trainable = make_trainable(optimizer=optax.adam(1e-2))
    r8 = AutoDist({"topology": {"num_devices": 8}},
                  AllReduce()).build(trainable)
    batches = [make_batch(s) for s in range(4)]
    for b in batches[:2]:
        r8.step(b)
    saver = Saver(str(tmp_path))
    saver.save(r8)
    assert saver.read_sidecar(2) is not None

    r4 = AutoDist({"topology": {"num_devices": 4}},
                  PS()).build(make_trainable(optimizer=optax.adam(1e-2),
                                             seed=9))
    saver.restore_elastic(r4)
    assert r4.step_count == 2
    for b in batches[2:]:
        r4.step(b)
    expected = single_device_reference(
        make_trainable(optimizer=optax.adam(1e-2)), batches)
    assert_trees_close(r4.get_params(), jax.device_get(expected),
                       rtol=2e-4, atol=1e-5)


def test_grow_4_to_8_bit_exact_restore(tmp_path):
    trainable = make_trainable(optimizer=_momentum())
    r4 = AutoDist({"topology": {"num_devices": 4}},
                  PS()).build(trainable)
    for s in range(2):
        r4.step(make_batch(s))
    saver = Saver(str(tmp_path))
    saver.save(r4)
    r8 = AutoDist({"topology": {"num_devices": 8}}, AllReduce()).build(
        make_trainable(optimizer=_momentum(), seed=9))
    saver.restore_elastic(r8)
    assert_trees_equal(r8.get_params(), r4.get_params())
    assert r8.step_count == 2
    m = r8.step(make_batch(5))
    assert np.isfinite(float(m["loss"]))


# --------------------------------------------------------------------------- #
# The fast path: same devices, ONE compiled program, ADT110-clean
# --------------------------------------------------------------------------- #
def test_fast_path_single_program_and_lint():
    from autodist_tpu.analysis import lint_program, rules_for_reshard

    trainable = make_trainable(optimizer=optax.adam(1e-2))
    src = AutoDist({"topology": {"num_devices": 8}},
                   AllReduce()).build(trainable)
    batches = [make_batch(s) for s in range(4)]
    for b in batches[:2]:
        src.step(b)
    dst = AutoDist({"topology": {"num_devices": 8}},
                   PartitionedPS()).build(
        make_trainable(optimizer=optax.adam(1e-2), seed=9))
    dst.state = reshard_state(src.lowered, src.state, dst.lowered)
    for b in batches[2:]:
        dst.step(b)
    expected = single_device_reference(
        make_trainable(optimizer=optax.adam(1e-2)), batches)
    assert_trees_close(dst.get_params(), jax.device_get(expected),
                       rtol=2e-4, atol=1e-5)

    # the transfer is ONE compiled program honoring the reshard
    # contract: no host transfer, no gather beyond the target-shard
    # budget (acceptance: hlo_probe/ADT110 territory)
    convert, _ = build_convert_fn(src.lowered, src.state, dst.lowered)
    text = convert.lower(src.state).compile().as_text()
    budget = shard_budget((dst.lowered, dst.state))
    report = lint_program(text, rules_for_reshard(budget),
                          where="fast-path")
    assert report.ok, report.render()


def test_corpus_reshard_program_routes_without_full_gather():
    """The corpus reshard (axis-0 -> axis-1 shards: every element
    changes owner) compiles to shard-granular collective routes; the
    naive gather-to-replicated sibling fires ADT110."""
    from autodist_tpu.analysis import (lint_program, programs,
                                       rules_for_reshard)

    budget = programs.reshard_budget()
    rules = rules_for_reshard(budget)
    honest = lint_program(programs.reshard_step_text(), rules,
                          where="honest")
    assert honest.ok, honest.render()
    naive = lint_program(programs.reshard_step_text(naive=True), rules,
                         where="naive")
    assert "ADT110" in naive.codes()


def test_reshard_mutations_fire():
    from autodist_tpu.analysis.mutations import run_mutations

    results = run_mutations(kinds=["reshard"])
    assert {r["code"] for r in results} == {"ADT070", "ADT071"}
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


def test_lint_zoo_reshard_budget_guard_is_loud():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_strategy
    finally:
        sys.path.pop(0)
    _, _, results = lint_strategy.lint_zoo(
        max_programs=0, decode=False, reshard=True,
        out=lambda *a, **k: None)
    skipped = [r["candidate"] for r in results
               if r.get("program") == "skipped (--max-programs budget)"]
    assert "reshard/axis0->axis1" in skipped


# --------------------------------------------------------------------------- #
# Pipeline family: dp/pp/tp changes, vocab pad edge, ZeRO-3
# --------------------------------------------------------------------------- #
V_ODD = 93   # V % tp != 0 at tp=2: the zero-pad edge


def make_lm(layers=2, vocab=V_ODD):
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=vocab, hidden_size=16,
                            num_layers=layers, num_heads=2, mlp_dim=32,
                            max_len=8, dtype=jnp.float32,
                            dropout_rate=0.0, attention_dropout_rate=0.0)
    return make_pipeline_lm_trainable(cfg, _momentum(),
                                      jax.random.PRNGKey(0))


def lm_batches(k=4, vocab=V_ODD):
    r = np.random.RandomState(0)
    return [{"x": r.randint(0, vocab, (8, 8)).astype(np.int32),
             "y": r.randint(0, vocab, (8, 8)).astype(np.int32)}
            for _ in range(k)]


def run_steps(runner, batches, start):
    for i, b in enumerate(batches):
        runner.step(b, rng=jax.random.PRNGKey(start + i))
    return runner


def test_tp_change_with_vocab_pad_live_golden():
    """tp=2 vocab-parallel (V=93 -> padded 94) re-laid live as tp=1
    dp=4: the reshard un-pads and re-replicates the table, the
    trajectory matches never having switched."""
    specA = {"topology": {"num_devices": 8},
             "mesh": {"data": 2, "pipe": 2, "model": 2}}
    specB = {"topology": {"num_devices": 8},
             "mesh": {"data": 4, "pipe": 2}}
    batches = lm_batches()

    ref = AutoDist(specA, "Pipeline", num_microbatches=2,
                   tensor_parallel=2, vocab_parallel=True).build(make_lm())
    run_steps(ref, batches, 0)
    ref_params = ref.lowered.unpad_params(ref.state["params"])

    src = AutoDist(specA, "Pipeline", num_microbatches=2,
                   tensor_parallel=2, vocab_parallel=True).build(make_lm())
    run_steps(src, batches[:2], 0)
    pre = src.lowered.unpad_params(src.state["params"])
    dst = AutoDist(specB, "Pipeline", num_microbatches=2).build(make_lm())
    dst.state = reshard_state(src.lowered, src.state, dst.lowered)
    assert int(dst.state["step"]) == 2
    assert_trees_equal(dst.lowered.unpad_params(dst.state["params"]), pre)
    run_steps(dst, batches[2:], 2)
    assert_trees_close(dst.lowered.unpad_params(dst.state["params"]),
                       ref_params)


def test_zero3_pp_change_grow_checkpoint_golden(tmp_path):
    """ZeRO-3 flat shards on {data:2, pipe:2} x V=2 restored as plain
    storage on {data:2, pipe:4} x V=1 — a zero-stage + pp + device
    count change through the checkpoint path, bit-exact at the
    reshard and trajectory-close after."""
    specA = {"topology": {"num_devices": 4},
             "mesh": {"data": 2, "pipe": 2}}
    specB = {"topology": {"num_devices": 8},
             "mesh": {"data": 2, "pipe": 4}}
    batches = lm_batches(vocab=37)

    ref = AutoDist(specA, "Pipeline", num_microbatches=2,
                   virtual_stages=2, zero_stage=3).build(
        make_lm(layers=4, vocab=37))
    run_steps(ref, batches, 0)
    ref_params = ref.lowered.unpad_params(ref.state["params"])

    src = AutoDist(specA, "Pipeline", num_microbatches=2,
                   virtual_stages=2, zero_stage=3).build(
        make_lm(layers=4, vocab=37))
    run_steps(src, batches[:2], 0)
    pre = src.lowered.unpad_params(src.state["params"])
    saver = Saver(str(tmp_path))
    saver.save(src)

    dst = AutoDist(specB, "Pipeline", num_microbatches=2).build(
        make_lm(layers=4, vocab=37))
    saver.restore_elastic(dst)
    assert_trees_equal(dst.lowered.unpad_params(dst.state["params"]), pre)
    run_steps(dst, batches[2:], 2)
    assert_trees_close(dst.lowered.unpad_params(dst.state["params"]),
                       ref_params)


# --------------------------------------------------------------------------- #
# Compressor error-feedback state
# --------------------------------------------------------------------------- #
def test_bf16_ef_state_rides_the_elastic_restore(tmp_path):
    """Same layout through the elastic path: EF residual rows transfer
    verbatim, so the resumed trajectory is BIT-identical to the
    uninterrupted one."""
    def make():
        return make_trainable(optimizer=optax.sgd(0.1))

    rA = AutoDist({"topology": {"num_devices": 4}},
                  AllReduce(compressor="bf16_ef")).build(make())
    batches = [make_batch(s) for s in range(4)]
    for b in batches[:2]:
        rA.step(b)
    saver = Saver(str(tmp_path))
    saver.save(rA)
    rB = AutoDist({"topology": {"num_devices": 4}},
                  AllReduce(compressor="bf16_ef")).build(
        make_trainable(optimizer=optax.sgd(0.1), seed=9))
    saver.restore_elastic(rB)
    for b in batches[2:]:
        rA.step(dict(b))
        rB.step(dict(b))
    assert_trees_equal(rA.get_params(), rB.get_params())


def test_bf16_ef_dp_change_reseeds_with_warning(tmp_path):
    """dp 4 -> 8 changes the per-device residual layout: the plan lint
    reports ADT071 (re-seeded, warned — never an error) and training
    continues."""
    rA = AutoDist({"topology": {"num_devices": 4}},
                  AllReduce(compressor="bf16_ef")).build(
        make_trainable())
    rA.step(make_batch(0))
    saver = Saver(str(tmp_path))
    saver.save(rA)
    rB = AutoDist({"topology": {"num_devices": 8}},
                  AllReduce(compressor="bf16_ef")).build(
        make_trainable(seed=3))
    src_m = saver.read_sidecar(1)["manifest"]
    dst_m = rB.lowered.state_manifest(rB.state)
    plan = plan_reshard(src_m, dst_m)
    assert plan.ok                       # warnings only
    assert {d.code for d in plan.report.warnings} == {"ADT071"}
    assert plan.sync_reinit
    saver.restore_elastic(rB)
    m = rB.step(make_batch(1))
    assert np.isfinite(float(m["loss"]))


# --------------------------------------------------------------------------- #
# Compatibility lint and the pre-elastic escape hatch
# --------------------------------------------------------------------------- #
def test_reshard_mismatch_is_coded_error():
    src = AutoDist({"topology": {"num_devices": 4}},
                   PS()).build(make_trainable())
    dst = AutoDist({"topology": {"num_devices": 4}}, PS()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    with pytest.raises(ReshardError) as e:
        reshard_state(src.lowered, src.state, dst.lowered)
    assert "ADT070" in str(e.value)


def test_pre_elastic_checkpoint_demands_strategy(tmp_path):
    runner = AutoDist({"topology": {"num_devices": 8}},
                      PartitionedPS()).build(make_trainable())
    runner.step(make_batch(0))
    saver = Saver(str(tmp_path))
    saver.save(runner)
    os.remove(saver._sidecar_path(1))    # simulate a pre-elastic save

    target = AutoDist({"topology": {"num_devices": 4}}, PS()).build(
        make_trainable(seed=9))
    with pytest.raises(ValueError, match="layout-unknown"):
        saver.restore_elastic(target)
    with pytest.raises(ValueError, match="strategy="):
        saver.restore_elastic(target)
    # the escape hatch: pass the writer's Strategy, the source layout
    # is rebuilt on a simulated mesh and the restore proceeds
    saver.restore_elastic(target, strategy=runner.strategy)
    assert_trees_equal(target.get_params(), runner.get_params())


# --------------------------------------------------------------------------- #
# Telemetry: the reshard record + gauges, schema-gated
# --------------------------------------------------------------------------- #
def test_reshard_record_schema_and_report(tmp_path):
    from autodist_tpu import telemetry

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)

    telemetry.reset()
    out = tmp_path / "run"
    telemetry.configure(out_dir=str(out))
    try:
        src = AutoDist({"topology": {"num_devices": 8}},
                       AllReduce()).build(make_trainable())
        dst = AutoDist({"topology": {"num_devices": 8}},
                       PartitionedPS()).build(make_trainable(seed=9))
        dst.state = reshard_state(src.lowered, src.state, dst.lowered)
        telemetry.flush()
    finally:
        telemetry.reset()
    assert telemetry_report.check_schema(str(out)) == []
    records = telemetry_report.load_jsonl(str(out / "metrics.jsonl"))
    reshards = [r for r in records if r.get("kind") == "reshard"]
    assert len(reshards) == 1 and reshards[0]["route"] == "compiled"
    assert reshards[0]["peak_host_bytes"] == 0
    gauges = {r["name"] for r in records if r.get("kind") == "gauge"}
    assert {"reshard/bytes_moved", "reshard/peak_host_bytes"} <= gauges
    assert "## reshards" in telemetry_report.render(str(out))
    # a doctored record breaks the schema gate
    bad = dict(reshards[0])
    bad.pop("bytes_moved")
    with open(out / "metrics.jsonl", "a") as f:
        f.write(json.dumps(bad) + "\n")
    assert any("reshard record missing" in p
               for p in telemetry_report.check_schema(str(out)))


# --------------------------------------------------------------------------- #
# CLI + controller
# --------------------------------------------------------------------------- #
def test_reshard_ckpt_cli(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import reshard_ckpt
    finally:
        sys.path.pop(0)

    runner = AutoDist({"topology": {"num_devices": 8}},
                      AllReduce()).build(make_cli_trainable())
    runner.step(make_batch(0))
    src_dir = tmp_path / "src"
    Saver(str(src_dir)).save(runner)
    out_dir = tmp_path / "out"
    rc = reshard_ckpt.main([
        str(src_dir), str(out_dir),
        "--trainable", "tests.unit.test_elastic:make_cli_trainable",
        "--auto-search", "--num-devices", "4"])
    assert rc == 0
    out_saver = Saver(str(out_dir))
    assert out_saver.latest_step() == 1
    assert out_saver.read_sidecar(1) is not None  # re-resharding works
    target = AutoDist({"topology": {"num_devices": 4}}, PS()).build(
        make_cli_trainable())
    out_saver.restore_elastic(target)
    assert_trees_close(target.get_params(), runner.get_params(),
                       rtol=1e-6, atol=0)


def test_portable_checkpoint_with_strategy_is_coded_error(tmp_path):
    """A portable (params-only) save cannot feed a FULL elastic
    restore: the missing optimizer leaves are a coded error caught
    BEFORE assembly, pointing at restore_portable — never a bare
    KeyError mid-reshard."""
    runner = AutoDist({"topology": {"num_devices": 8}},
                      PS()).build(make_trainable(optimizer=optax.adam(1e-2)))
    runner.step(make_batch(0))
    saver = Saver(str(tmp_path))
    saver.save(runner, portable=True)
    target = AutoDist({"topology": {"num_devices": 4}}, PS()).build(
        make_trainable(optimizer=optax.adam(1e-2), seed=9))
    with pytest.raises(ValueError, match="restore_portable"):
        saver.restore_elastic(target, strategy=runner.strategy)


def test_preemption_save_failure_still_hands_off(tmp_path):
    """exit_after=False + a failing checkpoint: the handler logs,
    reports through on_preempted(saved=False), and does NOT raise into
    the interrupted frame — the loop still sees preempted and falls
    back to the last good checkpoint."""
    import signal

    trainable = make_trainable(optimizer=_momentum())
    runner = AutoDist({"topology": {"num_devices": 8}},
                      AllReduce()).build(trainable)
    saver = Saver(str(tmp_path))
    ctl = ElasticController(trainable, saver, global_batch=16)
    previous = ctl.install(runner)
    try:
        runner.step(make_batch(0))
        saver.save(runner)            # the last GOOD checkpoint (step 1)
        runner.step(make_batch(1))

        def broken_save(*a, **k):
            raise OSError("disk full")

        saver.save = broken_save
        os.kill(os.getpid(), signal.SIGTERM)   # must not raise here
        assert ctl.preempted
        del saver.save                          # restore the real save
        resumed = ctl.resume({"num_devices": 4})
        assert resumed.step_count == 1          # the last good step
    finally:
        for sig, prev in previous.items():
            signal.signal(sig, prev if callable(prev)
                          or prev in (signal.SIG_IGN, signal.SIG_DFL)
                          else signal.SIG_DFL)


def test_sync_transfer_requires_same_compressor():
    """Identical (rows, width) is NOT enough: bf16_ef residuals mean
    nothing to another compressor — transfer only on matching
    semantics, else re-seed with ADT071."""
    from autodist_tpu.analysis import lint_reshard

    def manifest(comp):
        return {"leaves": {"sync_state/g0:x": {
                    "stored_shape": [4, 16], "logical_shape": [4, 16],
                    "dtype": "float32", "ops": []}},
                "sync": {"sync_state/g0:x": {
                    "rows": 4, "width": 16, "compressor": comp}}}

    same = plan_reshard(manifest("bf16_ef"), manifest("bf16_ef"))
    assert same.sync_transfer and not same.sync_reinit
    crossed = plan_reshard(manifest("bf16_ef"), manifest("int8_ef"))
    assert crossed.sync_reinit and not crossed.sync_transfer
    assert "ADT071" in lint_reshard(manifest("bf16_ef"),
                                    manifest("int8_ef")).codes()


def test_controller_hook_follows_resume(tmp_path):
    """A second preemption after resume() must checkpoint the CURRENT
    runner (post-resume step), not the stale install-time one."""
    import signal

    trainable = make_trainable(optimizer=_momentum())
    runner = AutoDist({"topology": {"num_devices": 8}},
                      AllReduce()).build(trainable)
    saver = Saver(str(tmp_path))
    ctl = ElasticController(trainable, saver, global_batch=16)
    previous = ctl.install(runner)
    try:
        for s in range(2):
            runner.step(make_batch(s))
        os.kill(os.getpid(), signal.SIGTERM)
        assert ctl.preempted and saver.latest_step() == 2
        resumed = ctl.resume({"num_devices": 4})
        # the pre-shrink runner's device state was released before the
        # new build (no double residency on the survivors)
        assert runner.state is None
        resumed.step(make_batch(2))
        os.kill(os.getpid(), signal.SIGTERM)    # second preemption
        assert saver.latest_step() == 3         # the RESUMED runner's step
    finally:
        for sig, prev in previous.items():
            signal.signal(sig, prev if callable(prev)
                          or prev in (signal.SIG_IGN, signal.SIG_DFL)
                          else signal.SIG_DFL)


def test_controller_hot_swap_preserves_trajectory():
    from autodist_tpu.resource import ResourceSpec

    trainable = make_trainable(optimizer=_momentum())
    runner = AutoDist({"topology": {"num_devices": 8}},
                      AllReduce()).build(trainable)
    batches = [make_batch(s) for s in range(4)]
    for b in batches[:2]:
        runner.step(b)
    ctl = ElasticController(trainable, saver=None, global_batch=16)
    spec = ResourceSpec({"topology": {"num_devices": 8}})
    strategy = PartitionedPS().build(trainable, spec)
    swapped = ctl.hot_swap(runner, strategy=strategy, spec=spec)
    for b in batches[2:]:
        swapped.step(b)
    expected = single_device_reference(
        make_trainable(optimizer=_momentum()), batches)
    assert_trees_close(swapped.get_params(), jax.device_get(expected))


@pytest.mark.slow
def test_preemption_shrink_research_resume_subprocess(tmp_path):
    """Acceptance: a SIGTERM-preempted run checkpoints, re-elects on
    the surviving (simulated) topology via simulator/search, reshards,
    and resumes — end to end in a subprocess that observes the
    signal."""
    script = tmp_path / "elastic_preempt.py"
    script.write_text(f"""
import os, signal
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
os.environ['JAX_PLATFORMS'] = 'cpu'
import sys
sys.path.insert(0, {REPO!r})
import numpy as np, optax
from autodist_tpu import AllReduce, AutoDist
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.elastic import ElasticController
from tests.unit.test_end_to_end import make_batch, make_trainable

t = make_trainable(optimizer=optax.sgd(0.1, momentum=0.9))
runner = AutoDist({{"topology": {{"num_devices": 8}}}}, AllReduce()).build(t)
ctl = ElasticController(t, Saver({str(tmp_path / 'ckpt')!r}),
                        global_batch=16)
ctl.install(runner)
for s in range(2):
    runner.step(make_batch(s))
os.kill(os.getpid(), signal.SIGTERM)   # simulated preemption
assert ctl.preempted, "signal handler did not run"
runner = ctl.resume({{"num_devices": 4}})
assert runner.step_count == 2
assert len(list(runner.mesh.devices.flat)) == 4
m = runner.step(make_batch(2))
assert np.isfinite(float(np.asarray(m["loss"])))
print("ELASTIC_RESUME_OK", ctl.last_result.winner.name)
""")
    proc = subprocess.run([sys.executable, str(script)], cwd=REPO,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "ELASTIC_RESUME_OK" in proc.stdout
