"""End-to-end numeric golden tests on the simulated 8-device mesh.

Style of the reference's c0 case (``tests/integration/cases/c0.py:88-138``):
assert the *exact post-update parameter values* under each strategy — not
just liveness.  The single-device reference result (plain SGD on the mean
gradient over the global batch) must be reproduced bit-close by every
strategy lowering.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, Parallax, PartitionedAR,
                          PartitionedPS, PS, PSLoadBalancing,
                          RandomAxisPartitionAR, Trainable,
                          UnevenPartitionedPS, ZeRO)

BATCH = 16
DIM = 6
OUT = 3


def make_trainable(optimizer=None, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "dense": {"w": jnp.asarray(rng.randn(DIM, OUT), jnp.float32),
                  "b": jnp.zeros((OUT,), jnp.float32)},
        "scale": jnp.ones((), jnp.float32),
    }

    def loss_fn(p, batch):
        pred = batch["x"] @ p["dense"]["w"] + p["dense"]["b"]
        pred = pred * p["scale"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return Trainable.from_loss_fn(
        loss_fn, params, optimizer or optax.sgd(0.1))


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(BATCH, DIM).astype(np.float32),
            "y": rng.randn(BATCH, OUT).astype(np.float32)}


def single_device_reference(trainable, batches):
    """Ground truth: plain optax loop on one device, full batch."""
    params = trainable.params
    opt_state = trainable.optimizer.init(params)

    def loss_for(p, b):
        l, _, _ = trainable.loss(p, None, b, jax.random.PRNGKey(0))
        return l

    for b in batches:
        grads = jax.grad(loss_for)(params, jax.tree.map(jnp.asarray, b))
        updates, opt_state = trainable.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    return params


STRATEGIES = [
    ("AllReduce", lambda: AllReduce(chunk_size=2)),
    ("AllReduce-chunk1", lambda: AllReduce(chunk_size=1)),
    ("PS", lambda: PS()),
    ("PSLoadBalancing", lambda: PSLoadBalancing()),
    ("PartitionedPS", lambda: PartitionedPS()),
    ("UnevenPartitionedPS", lambda: UnevenPartitionedPS()),
    ("PartitionedAR", lambda: PartitionedAR()),
    ("RandomAxisPartitionAR", lambda: RandomAxisPartitionAR(seed=3)),
    ("Parallax", lambda: Parallax()),
    ("ZeRO1", lambda: ZeRO(stage=1)),
    ("ZeRO2", lambda: ZeRO(stage=2)),
    ("ZeRO3", lambda: ZeRO(stage=3)),
]


@pytest.mark.parametrize("name,builder", STRATEGIES, ids=[s[0] for s in STRATEGIES])
def test_strategy_matches_single_device(name, builder):
    trainable = make_trainable()
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(make_trainable(), batches)

    ad = AutoDist({}, builder())
    runner = ad.build(trainable)
    for b in batches:
        runner.step(b)
    got = runner.get_params()

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
        got, jax.device_get(expected))
    assert runner.step_count == 3


@pytest.mark.parametrize("opt_name,opt", [
    ("adam", optax.adam(1e-2)),
    ("adamw", optax.adamw(1e-2, weight_decay=0.01)),
    ("momentum", optax.sgd(0.1, momentum=0.9)),
    ("rmsprop", optax.rmsprop(1e-2)),
    ("adagrad", optax.adagrad(0.1)),
])
@pytest.mark.parametrize("strategy", ["PS", "PartitionedPS", "PartitionedAR",
                                      "AllReduce"])
def test_optimizers_under_sharded_state(opt_name, opt, strategy):
    """The reference validated update-op detection across 14 optimizer
    configs (``test_graph_item.py:53-84``); here each optimizer's state
    must shard correctly under every update-space layout."""
    trainable = make_trainable(optimizer=opt)
    batches = [make_batch(s) for s in range(2)]
    expected = single_device_reference(make_trainable(optimizer=opt), batches)

    from autodist_tpu.strategy import builders
    runner = AutoDist({}, builders.create(strategy)).build(trainable)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5),
        runner.get_params(), jax.device_get(expected))


def test_metrics_replicated_and_correct():
    trainable = make_trainable()
    batch = make_batch()
    runner = AutoDist({}, AllReduce()).build(trainable)

    # loss metric == single-device full-batch loss at step 0
    def loss_for(p, b):
        l, _, _ = trainable.loss(p, None, b, jax.random.PRNGKey(0))
        return l

    expected = loss_for(trainable.params, jax.tree.map(jnp.asarray, batch))
    metrics = runner.step(batch)
    np.testing.assert_allclose(float(metrics["loss"]), float(expected),
                               rtol=1e-5)


@pytest.mark.parametrize("name,builder", [
    ("AllReduce", lambda: AllReduce()),
    ("PartitionedPS", lambda: PartitionedPS()),
    ("ZeRO1", lambda: ZeRO(stage=1)),
], ids=["AllReduce", "PartitionedPS", "ZeRO1"])
def test_control_flow_model_matches_single_device(name, builder):
    """Reference c4/c6 analog (``tests/integration/cases/c4.py:22-30``,
    dynamic-LSTM c6): structured control flow — lax.while_loop and
    lax.scan — inside the loss must lower and reproduce single-device
    numerics under every strategy family."""
    def make():
        rng = np.random.RandomState(3)
        params = {"cell": jnp.asarray(rng.randn(DIM, DIM) * 0.1, jnp.float32),
                  "out": jnp.asarray(rng.randn(DIM, 1) * 0.1, jnp.float32)}

        def loss_fn(p, batch):
            # while_loop with a data-dependent bound (c4's tf.while_loop
            # analog).  Reverse-mode AD cannot cross a while_loop, so it
            # feeds the differentiable path through stop_gradient — the
            # reference likewise never differentiated through its c4 loop
            # condition.
            def cond(c):
                i, h = c
                return (i < 3) & (jnp.linalg.norm(h) < 1e3)

            def body(c):
                i, h = c
                return i + 1, jnp.tanh(h @ p["cell"])

            _, h0 = jax.lax.while_loop(
                cond, body, (0, jax.lax.stop_gradient(batch["x"])))
            h = batch["x"] + jax.lax.stop_gradient(h0 - batch["x"])
            # scan: accumulate a short recurrence over a fixed horizon
            # (per-example emissions — the DP feed contract requires an
            # example-decomposable loss).
            def step(carry, _):
                carry = jnp.tanh(carry @ p["cell"])
                return carry, carry.mean(axis=-1)
            h, outs = jax.lax.scan(step, h, None, length=4)
            pred = (h @ p["out"])[:, 0] + outs.sum(axis=0)
            return jnp.mean((pred - batch["y"]) ** 2)

        return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.05))

    batches = []
    rng = np.random.RandomState(11)
    for s in range(3):
        batches.append({"x": rng.randn(BATCH, DIM).astype(np.float32),
                        "y": rng.randn(BATCH).astype(np.float32)})
    expected = single_device_reference(make(), batches)
    runner = AutoDist({}, builder()).build(make())
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-6, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_rerun_bit_identical_determinism():
    """§5.2 invariant: rebuilding and rerunning the same (trainable,
    strategy, data) is bit-identical — no nondeterministic collectives,
    no uninitialized state, stable device order."""
    def run():
        runner = AutoDist({}, Parallax()).build(make_trainable(seed=3))
        for s in range(3):
            runner.step(make_batch(s))
        return runner.get_params()

    a, b = run(), run()
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


def test_allreduce_single_replica_matches_reference():
    """n=1 takes the bucketing bypass (the allreduce is an identity);
    numerics must still match the plain optax loop exactly."""
    trainable = make_trainable(optimizer=optax.adam(1e-2))
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(
        make_trainable(optimizer=optax.adam(1e-2)), batches)

    ad = AutoDist({"topology": {"num_devices": 1}}, AllReduce(chunk_size=2))
    runner = ad.build(trainable)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7),
        runner.get_params(), jax.device_get(expected))
