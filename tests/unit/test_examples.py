"""Example/benchmark scripts smoke tests.

The reference's integration tier ran its example case files end-to-end
per strategy (SURVEY.md §4); here each script runs as a subprocess on a
small simulated CPU mesh with tiny sizes.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


pytestmark = pytest.mark.slow

def run_script(rel_path, *args, timeout=240):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, rel_path), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return proc.stdout


def test_bench_cpu_smoke():
    """The driver-facing bench must emit one scored JSON record on its
    CPU dev-smoke path (score-first: the record exists even if the
    opportunistic tuning stages never run)."""
    import json
    out = run_script("bench.py")
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "bert_base_mlm_mfu"
    assert rec["scored"] is True and "error" not in rec
    # toy-model MFU rounds to 0.0; the rate is the liveness signal
    assert rec["examples_per_sec"] > 0 and rec["step_ms"] > 0


def test_linear_regression():
    out = run_script("examples/linear_regression.py", "--steps", "6")
    assert "loss=" in out


def test_image_classifier():
    out = run_script("examples/image_classifier.py", "--steps", "4",
                     "--batch-size", "16")
    assert "loss=" in out


def test_sentiment_classifier_partitioned_ps():
    out = run_script("examples/sentiment_classifier.py", "--steps", "4",
                     "--strategy", "PartitionedPS", "--vocab-size", "1000")
    assert "loss=" in out


def test_lm1b_parallax():
    out = run_script("examples/lm1b_train.py", "--steps", "4",
                     "--vocab-size", "2000")
    assert "loss=" in out


def test_benchmark_imagenet_tiny():
    out = run_script("examples/benchmark/imagenet.py", "--model", "resnet18",
                     "--preset", "tiny", "--train-steps", "4",
                     "--log-steps", "2", "--warmup-steps", "1")
    assert "examples_per_sec_final" in out
    assert "resnet18/AllReduce" in out


def test_benchmark_imagenet_per_step_loop():
    """--steps-per-loop 1 keeps the legacy per-step timed loop (true
    per-step latency percentiles via the prefetching DataLoader)."""
    out = run_script("examples/benchmark/imagenet.py", "--model", "resnet18",
                     "--preset", "tiny", "--train-steps", "4",
                     "--log-steps", "2", "--warmup-steps", "1",
                     "--steps-per-loop", "1")
    assert "examples_per_sec_final" in out
    assert "step_ms_p50" in out          # per-step stat, not window-derived
    assert "steps_per_loop" not in out   # fused-path keys absent


def test_benchmark_imagenet_batch_probe(monkeypatch):
    """The self-tuning batch probe (exercised via the candidate override)
    times each size, picks the examples/sec winner, and reports its
    per-chip batch in the JSON headline."""
    monkeypatch.setenv("AUTODIST_TPU_BATCH_CANDIDATES", "1,2")
    out = run_script("examples/benchmark/imagenet.py", "--model",
                     "resnet18", "--preset", "tiny", "--train-steps",
                     "2", "--log-steps", "2", "--warmup-steps", "1",
                     "--json", timeout=300)
    # both probes must SUCCEED (the failure form prints "failed:")
    assert len([l for l in out.splitlines()
                if l.startswith("# probe batch") and "ex/s" in l]) == 2
    assert not [l for l in out.splitlines()
                if l.startswith("# probe batch") and "failed" in l]
    import json as _json
    headline = _json.loads(
        [l for l in out.splitlines() if '"metric"' in l][-1])
    assert headline["batch_per_chip"] in (1, 2)


def test_benchmark_bert_tiny_flash(tmp_path):
    out = run_script("examples/benchmark/bert.py", "--preset", "tiny",
                     "--train-steps", "4", "--log-steps", "2",
                     "--warmup-steps", "1", "--flash-attention",
                     "--benchmark-log-dir", str(tmp_path))
    assert "MFU" in out
    assert (tmp_path / "metric.log").exists()


def test_benchmark_ncf_tiny():
    out = run_script("examples/benchmark/ncf.py", "--preset", "tiny",
                     "--train-steps", "4", "--log-steps", "2",
                     "--warmup-steps", "1")
    assert "ncf/AllReduce" in out


def test_long_context_sequence_parallel():
    out = run_script("examples/long_context.py", "--steps", "2",
                     "--seq-len", "64", "--seq-parallel", "4",
                     "--hidden", "32", "--layers", "1", timeout=300)
    assert "long-context" in out and "sp=4" in out


def test_long_context_ring_flash():
    out = run_script("examples/long_context.py", "--steps", "2",
                     "--seq-len", "64", "--seq-parallel", "4",
                     "--hidden", "32", "--layers", "1", "--flash",
                     timeout=300)
    assert "attn=flash" in out and "sp=4" in out


def test_pipeline_train_interleaved():
    out = run_script("examples/pipeline_train.py", "--steps", "3",
                     "--virtual-stages", "2", "--microbatches", "2",
                     "--hidden", "16", "--batch", "16", timeout=300)
    assert "virtual=2" in out and "bubble" in out and "loss=" in out


def test_pipeline_train_auto_search():
    """--auto-search on a simulated two-slice topology: the search
    report prints (counts, per-level frontier, winner knob string) and
    the elected plan trains."""
    out = run_script("examples/pipeline_train.py", "--steps", "3",
                     "--stages", "2", "--hidden", "16", "--batch", "16",
                     "--auto-search", "--num-slices", "2", timeout=300)
    assert "raw configs" in out and "pruned by dominance" in out
    assert "auto-search winner: dcn2_" in out and "loss=" in out


def test_moe_train_expert_parallel():
    out = run_script("examples/moe_train.py", "--steps", "3",
                     "--experts", "8", "--layers", "1", "--hidden", "32",
                     "--vocab", "64", "--seq-len", "16", "--batch", "16",
                     timeout=300)
    assert "experts over" in out and "aux=" in out
