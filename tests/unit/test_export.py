"""Serving-export round trip (≙ reference ``SavedModelBuilder``,
``checkpoint/saved_model_builder.py:42-59``; test bar
``tests/checkpoint/test_saved_model.py``): train distributed, export,
reload with no framework machinery, and get identical outputs."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, Parallax, PartitionedPS
from autodist_tpu.checkpoint import export_model, load_exported


pytestmark = pytest.mark.slow

def make_model():
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16, name="h")(x)
            return nn.Dense(3, name="out")(nn.relu(x))

    return Tiny()


def test_export_under_fsdp_roundtrip(tmp_path):
    from autodist_tpu.capture import Trainable

    model = make_model()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.float32))["params"]

    def loss_fn(p, batch):
        pred = model.apply({"params": p}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-2))
    runner = AutoDist({}, PartitionedPS()).build(trainable)
    rng = np.random.RandomState(0)
    for s in range(3):
        runner.step({"x": rng.randn(16, 8).astype(np.float32),
                     "y": rng.randn(16, 3).astype(np.float32)})

    def apply_fn(p, x):
        return model.apply({"params": p}, x)

    sample = np.zeros((4, 8), np.float32)
    path = export_model(str(tmp_path / "artifact"), apply_fn,
                        None, [sample], runner=runner)

    served = load_exported(path)
    x = rng.randn(4, 8).astype(np.float32)
    got = np.asarray(served(x))

    want = np.asarray(apply_fn(runner.get_params(), x))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    # The artifact's params are plain logical-name arrays.
    assert served.params["h"]["kernel"].shape == (8, 16)


def test_export_sharded_state_pipeline_roundtrip(tmp_path):
    """The sharded-state export pin: a runner whose parameters live as
    vocab-padded shards (vocab_parallel, V=33 odd) and ZeRO-3 flat
    shards must export through the gather/unpad path — ``params/``
    carries unpadded logical shapes — and reload on a single device
    bit-close to the live runner's own apply."""
    from autodist_tpu.checkpoint import load_exported_params
    from autodist_tpu.models.pipeline_lm import (make_pipeline_lm_trainable,
                                                 sequential_logits)
    from autodist_tpu.models.transformer import TransformerConfig

    V = 33
    cfg = TransformerConfig(vocab_size=V, hidden_size=16, num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    trainable = make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                           jax.random.PRNGKey(0))
    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 8},
                   "mesh": {"data": 2, "pipe": 2, "model": 2}},
                  "Pipeline", num_microbatches=2, tensor_parallel=2,
                  vocab_parallel=True, zero_stage=3)
    runner = ad.build(trainable)
    rng = np.random.RandomState(0)
    for _ in range(2):
        x = rng.randint(0, V, (8, 8)).astype(np.int32)
        runner.step({"x": x, "y": np.concatenate([x[:, 1:], x[:, :1]], 1)})

    def apply_fn(p, tokens):
        return sequential_logits(cfg, p, tokens)

    sample = np.zeros((2, 8), np.int32)
    path = export_model(str(tmp_path / "artifact"), apply_fn, None,
                        [sample], runner=runner)

    # params/ carries UNPADDED logical shapes (the vocab pad row and the
    # ZeRO-3 flat [C, chunk] storage both unwound)
    restored = load_exported_params(path)
    assert restored["shared"]["embedding"].shape == (V, 16)
    assert restored["stages"]["mlp"]["wi"]["kernel"].shape == (2, 16, 32)
    fetched = runner.get_params()
    jax.tree.map(np.testing.assert_array_equal, fetched,
                 jax.tree.map(np.asarray, restored))

    served = load_exported(path)
    toks = rng.randint(0, V, (2, 8)).astype(np.int32)
    got = np.asarray(served(toks))
    want = np.asarray(apply_fn(fetched, toks))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_export_sparse_embedding_model(tmp_path):
    """Vocab-sharded (Parallax) training exports an unpartitioned table."""
    from autodist_tpu.capture import Trainable
    from autodist_tpu.ops import embedding_lookup

    VOCAB, DIM = 64, 8
    rng = np.random.RandomState(0)
    params = {"embedding": jnp.asarray(rng.randn(VOCAB, DIM) * 0.1,
                                       jnp.float32),
              "w": jnp.asarray(rng.randn(DIM, 1) * 0.1, jnp.float32)}

    def loss_fn(p, batch):
        emb = embedding_lookup(p["embedding"], batch["ids"]).mean(axis=1)
        return jnp.mean(((emb @ p["w"])[:, 0] - batch["y"]) ** 2)

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                       sparse_params=("embedding",))
    runner = AutoDist({}, Parallax()).build(trainable)
    for s in range(2):
        runner.step({"ids": rng.randint(0, VOCAB, (16, 4)).astype(np.int32),
                     "y": rng.randn(16).astype(np.float32)})

    def apply_fn(p, ids):
        return embedding_lookup(p["embedding"], ids).mean(axis=1) @ p["w"]

    sample = np.zeros((4, 4), np.int32)
    path = export_model(str(tmp_path / "artifact"), apply_fn, None,
                        [sample], runner=runner)
    served = load_exported(path)
    assert served.params["embedding"].shape == (VOCAB, DIM)
    ids = rng.randint(0, VOCAB, (4, 4)).astype(np.int32)
    got = np.asarray(served(ids))
    want = np.asarray(apply_fn(runner.get_params(), ids))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
