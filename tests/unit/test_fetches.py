"""Arbitrary-tensor fetch (≙ reference ``session.run(fetches)``,
``remapper.py:125-185``): values tagged with ``autodist_tpu.fetch``
inside a loss surface as ``fetch/<name>`` step metrics under every
lowering — the VERDICT round-4 'done' bar: a per-layer activation norm
fetched under FSDP and under the pipeline."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AutoDist, PartitionedPS, PipelineTrainable,
                          Trainable, fetch)

pytestmark = pytest.mark.slow

DIM = 16


def make_mlp_trainable():
    r = np.random.RandomState(0)
    params = {f"layer{i}": {"w": jnp.asarray(r.randn(DIM, DIM) * 0.3,
                                             jnp.float32)}
              for i in range(3)}

    def loss_fn(p, batch):
        h = batch["x"]
        for i in range(3):
            h = jnp.tanh(h @ p[f"layer{i}"]["w"])
            fetch(f"act_norm_l{i}", jnp.linalg.norm(h) / h.shape[0])
        return jnp.mean((h - batch["y"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))


def batch(seed=0):
    r = np.random.RandomState(seed)
    return {"x": r.randn(8, DIM).astype(np.float32),
            "y": r.randn(8, DIM).astype(np.float32)}


def single_device_norms(trainable, b):
    with_metrics = trainable.loss(trainable.params, None,
                                  jax.tree.map(jnp.asarray, b), None)
    return {k: float(np.asarray(v)) for k, v in with_metrics[2].items()
            if k.startswith("fetch/")}


def test_fetch_under_fsdp_matches_single_device():
    """Per-layer activation norms fetched under FSDP (PartitionedPS):
    values equal the single-device computation (params replicated in
    compute; the norm is replica-invariant only for identical batches,
    so feed the same rows to every shard via batch duplication of the
    comparison: here we compare the cross-replica mean against the mean
    of per-shard norms computed on the same global batch)."""
    t = make_mlp_trainable()
    runner = AutoDist({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"data": 8}}, PartitionedPS()).build(t)
    b = batch()
    m = runner.step(b)
    got = {k: float(np.asarray(v)) for k, v in m.items()
           if k.startswith("fetch/")}
    assert set(got) == {f"fetch/act_norm_l{i}" for i in range(3)}

    # expected: mean over shards of the per-shard norm
    t_ref = make_mlp_trainable()
    expect = {}
    for i in range(8):
        shard = {k: v[i] for k, v in
                 jax.tree.map(lambda a: a.reshape(8, 1, *a.shape[1:]),
                              b).items()}
        norms = single_device_norms(t_ref, shard)
        for k, v in norms.items():
            expect[k] = expect.get(k, 0.0) + v / 8
    for k in got:
        np.testing.assert_allclose(got[k], expect[k], rtol=1e-4)


def test_fetch_under_pipeline_loss_head():
    """The pipeline loss head can tag fetches; they get last-stage
    masking + broadcast like other head metrics."""
    S, H = 4, 8
    r = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r.randn(S, H, H) * 0.4, jnp.float32)}

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    def head(outputs, b):
        fetch("head_out_norm", jnp.linalg.norm(outputs) /
              outputs.shape[0])
        return jnp.mean((outputs - b["y"]) ** 2), {}

    t = PipelineTrainable(stage, stacked, head, optax.sgd(0.05),
                          num_stages=S)
    runner = AutoDist({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"data": 2, "pipe": 4}},
                      "Pipeline", num_microbatches=2).build(t)
    bh = {"x": r.randn(8, H).astype(np.float32),
          "y": r.randn(8, H).astype(np.float32)}
    m = runner.step(bh)
    v = float(np.asarray(m["fetch/head_out_norm"]))
    assert np.isfinite(v) and v > 0

    # sequential reference computes the same head norm on the full batch
    seq = t.loss(t.params, None, jax.tree.map(jnp.asarray, bh), None)
    # pipeline value = mean over the 2 data shards of per-shard norms;
    # just sanity-bound it against the full-batch norm scale.
    ref = float(np.asarray(seq[2]["fetch/head_out_norm"]))
    assert abs(v - ref) / max(ref, 1e-6) < 0.5


def test_fetch_rides_accumulation_and_zero():
    """fetch composes with grad accumulation (scan ys) and ZeRO-1."""
    from autodist_tpu import GradAccumulation, SequenceParallel

    t = make_mlp_trainable()
    runner = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                       "mesh": {"data": 4}},
                      GradAccumulation(PartitionedPS(), steps=2)).build(t)
    m = runner.step(batch())
    assert np.isfinite(float(np.asarray(m["fetch/act_norm_l2"])))


def test_fetch_collision_with_metric_errors():
    """An explicit metric occupying the fetch/ namespace collides with
    a tag of the same name — silent overwrite would corrupt one."""
    def loss_fn(p, b):
        fetch("act", jnp.zeros(()))
        l = jnp.mean((b["x"] @ p["w"]) ** 2)
        return l, {"fetch/act": l}

    t = Trainable.from_loss_fn(
        loss_fn, {"w": jnp.ones((DIM, DIM), jnp.float32)}, optax.sgd(0.1))
    with pytest.raises(ValueError, match="collides"):
        t.loss(t.params, None,
               {"x": jnp.ones((2, DIM), jnp.float32)}, None)


def test_fetch_noop_outside_collector():
    """Model code using fetch runs unchanged under plain jax."""
    x = jnp.ones((3,))
    assert fetch("anything", x) is x


def test_fetch_duplicate_tag_raises():
    def loss_fn(p, b):
        for i in range(2):
            fetch("act_norm", jnp.zeros(()))  # constant name: error
        return jnp.mean((b["x"] @ p["w"]) ** 2)

    t = Trainable.from_loss_fn(
        loss_fn, {"w": jnp.ones((DIM, DIM), jnp.float32)}, optax.sgd(0.1))
    with pytest.raises(ValueError, match="already used"):
        t.loss(t.params, None,
               {"x": jnp.ones((2, DIM), jnp.float32)}, None)


def test_fetch_inside_scan_fails_loudly():
    """A tag inside a lax.scan body cannot escape; the guard names the
    tag instead of surfacing a distant UnexpectedTracerError."""
    from jax import lax

    def loss_fn(p, b):
        def body(c, _):
            h = jnp.tanh(c @ p["w"])
            fetch("scan_h", jnp.linalg.norm(h))
            return h, None

        h, _ = lax.scan(body, b["x"], None, length=2)
        return jnp.mean(h ** 2)

    t = Trainable.from_loss_fn(
        loss_fn, {"w": jnp.ones((DIM, DIM), jnp.float32)}, optax.sgd(0.1))
    with pytest.raises(ValueError, match="scan_h"):
        t.loss(t.params, None,
               {"x": jnp.ones((2, DIM), jnp.float32)}, None)


def test_fetch_inside_pipeline_stage_fails_loudly():
    """A tag inside stage_fn cannot escape the tick scan; the pipeline
    lowering rejects it naming the tag (instead of silently dropping it
    while the sequential reference loss reports it)."""
    S, H = 4, 8
    r = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r.randn(S, H, H) * 0.4, jnp.float32)}

    def stage(p, x):
        h = jnp.tanh(x @ p["w"])
        fetch("stage_h", jnp.linalg.norm(h))
        return h

    def head(outputs, b):
        return jnp.mean((outputs - b["y"]) ** 2), {}

    t = PipelineTrainable(stage, stacked, head, optax.sgd(0.05),
                          num_stages=S)
    runner = AutoDist(
        {"topology": {"platform": "cpu", "num_devices": 8},
         "mesh": {"data": 2, "pipe": 4}}, "Pipeline",
        num_microbatches=2).build(t)
    bh = {"x": r.randn(8, H).astype(np.float32),
          "y": r.randn(8, H).astype(np.float32)}
    with pytest.raises(Exception, match="stage_h"):
        runner.step(bh)  # trace time: the tag is named in the error
