"""fit(): the Model.fit-tier loop (reference integration case c7 —
train/evaluate through the distributed session in one call)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AllReduce, AutoDist, Trainable, fit
from autodist_tpu.checkpoint import Saver


def make_trainable(seed=0):
    k = jax.random.PRNGKey(seed)
    params = {"w": jax.random.normal(k, (32, 8)) * 0.1}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adamw(1e-2))


def source(step):
    r = np.random.RandomState(step)
    return {"x": r.randn(16, 32).astype(np.float32),
            "y": r.randn(16, 8).astype(np.float32)}


def test_fit_trains_and_reports():
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    hist = fit(runner, source, steps=12, log_every=4,
               eval_source=source, eval_every=6, eval_batches=2)
    assert runner.step_count == 12
    assert hist["examples_per_sec"] > 0
    logged = dict(hist["loss"])
    assert set(logged) == {4, 8, 12}
    assert [s for s, _ in hist["eval"]] == [6, 12]
    # Learning is asserted on the eval history: both entries average the
    # same fixed eval batches, so the comparison is apples-to-apples;
    # per-step train losses land on fresh random batches and are not
    # monotonic.
    evals = dict(hist["eval"])
    assert float(np.asarray(evals[12]["loss"])) \
        < float(np.asarray(evals[6]["loss"]))


def test_fit_resumes_from_saver(tmp_path):
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    saver = Saver(str(tmp_path))
    fit(runner, source, steps=5, saver=saver, log_every=0)
    assert saver.latest_step() == 5

    # a "restarted job": fresh runner, same fit call, picks up at 5 and
    # continues the data stream (source called with 5, 6, 7 — not 0..2)
    seen = []

    def tracking_source(step):
        seen.append(step)
        return source(step)

    runner2 = AutoDist({}, AllReduce()).build(make_trainable())
    hist = fit(runner2, tracking_source, steps=8, saver=saver, log_every=0)
    assert runner2.step_count == 8
    assert saver.latest_step() == 8
    assert seen == [5, 6, 7]
    # already-done target is a no-op
    hist = fit(runner2, source, steps=8, saver=saver, log_every=0)
    assert runner2.step_count == 8
    saver.close()


def test_fit_with_pipeline_runner(tmp_path):
    """fit() composes with the pipeline lowering: prefetch, periodic
    checkpointing, and preemption-style resume on a PipelineTrainable."""
    from autodist_tpu import PipelineTrainable
    from autodist_tpu.strategy.parallel_builders import Pipeline

    def make():
        r = np.random.RandomState(0)
        stacked = {"w": jnp.asarray(r.randn(4, 8, 8) * 0.3, jnp.float32)}

        def stage(p, x):
            return jax.nn.relu(x @ p["w"])

        def head(o, b):
            return jnp.mean((o - b["y"]) ** 2), {}

        return PipelineTrainable(stage, stacked, head, optax.sgd(0.05),
                                 num_stages=4)

    spec = {"topology": {"platform": "cpu", "num_devices": 4},
            "mesh": {"pipe": 4}}
    r = np.random.RandomState(1)

    def source(step):
        x = r.randn(8, 8).astype(np.float32)
        return {"x": x, "y": x * 0.5}

    saver = Saver(str(tmp_path))
    runner = AutoDist(spec, Pipeline(num_microbatches=2)).build(make())
    fit(runner, source, steps=4, saver=saver, save_every=2, log_every=0)
    assert runner.step_count == 4
    assert saver.latest_step() == 4

    # resume: a fresh runner continues from the checkpoint
    runner2 = AutoDist(spec, Pipeline(num_microbatches=2)).build(make())
    hist = fit(runner2, source, steps=6, saver=saver, log_every=0)
    assert runner2.step_count == 6
    saver.close()


def test_fit_steps_per_loop_matches_per_step():
    """Fused fit hits the same cadence boundaries and (with a per-step
    rng stream being the only divergence) the same logged step set; the
    loss history values match the per-step loop when rngs are immaterial
    (deterministic loss_fn)."""
    r1 = AutoDist({}, AllReduce()).build(make_trainable())
    h1 = fit(r1, source, steps=12, log_every=4,
             eval_source=source, eval_every=6, eval_batches=2)

    r2 = AutoDist({}, AllReduce()).build(make_trainable())
    h2 = fit(r2, source, steps=12, log_every=4,
             eval_source=source, eval_every=6, eval_batches=2,
             steps_per_loop=5)
    assert r2.step_count == 12
    assert [s for s, _ in h2["loss"]] == [s for s, _ in h1["loss"]]
    assert [s for s, _ in h2["eval"]] == [s for s, _ in h1["eval"]]
    np.testing.assert_allclose(
        [v for _, v in h2["loss"]], [v for _, v in h1["loss"]],
        rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        r2.get_params(), r1.get_params())


def test_fit_steps_per_loop_saves_on_cadence(tmp_path):
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    saver = Saver(str(tmp_path))
    fit(runner, source, steps=9, saver=saver, save_every=3,
        log_every=0, steps_per_loop=4)
    assert saver.latest_step() == 9
    assert runner.step_count == 9


def test_fit_steps_per_loop_ragged_final_batch():
    """An iterable source whose final batch is partial trains under the
    fused path (the ragged batch becomes its own window) — parity with
    the per-step loop, which just recompiles for the new shape."""
    batches = [source(i) for i in range(5)]
    batches.append({k: v[:8] for k, v in source(5).items()})  # ragged

    r1 = AutoDist({}, AllReduce()).build(make_trainable())
    fit(r1, list(batches), steps=6, log_every=0)

    r2 = AutoDist({}, AllReduce()).build(make_trainable())
    fit(r2, list(batches), steps=6, log_every=0, steps_per_loop=4)
    assert r2.step_count == 6
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        r2.get_params(), r1.get_params())
