"""Flash-attention kernel: numeric parity with plain einsum attention.

Runs the Pallas interpreter on the CPU harness; on TPU the same code
compiles to the fused kernel.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.models.transformer import dot_product_attention
from autodist_tpu.ops import flash_attention, make_attention_fn


pytestmark = pytest.mark.slow

def _inputs(b=2, l=128, h=4, d=32, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(b, l, h, d) * 0.3, dtype)
    return mk(), mk(), mk()


def _reference(q, k, v, causal):
    mask = None
    if causal:
        l = q.shape[1]
        mask = jnp.tril(jnp.ones((l, l), bool))[None, None]
    return dot_product_attention(q, k, v, mask, dtype=jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _inputs()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_forward_uneven_blocks():
    """Sequence split into multiple q and k blocks of different sizes."""
    q, k, v = _inputs(l=96)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=16)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("l", [100, 127, 4])
def test_indivisible_seq_pads_and_masks(l, causal):
    """Arbitrary sequence lengths (incl. prime and sub-tile) are padded to
    a block multiple and masked — numerics must still match, forward and
    backward."""
    q, k, v = _inputs(l=l, d=16)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch at l={l}")


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    q, k, v = _inputs(l=64, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_bfloat16_forward():
    q, k, v = _inputs(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    ref = _reference(q.astype(jnp.float32), k.astype(jnp.float32),
                     v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_transformer_integration():
    """TransformerLM with the flash attention_fn matches plain attention."""
    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)

    def make(attention_fn):
        cfg = TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            mlp_dim=64, max_len=64, dropout_rate=0.0,
            attention_dropout_rate=0.0, causal=True, dtype=jnp.float32,
            attention_fn=attention_fn)
        return TransformerLM(cfg)

    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)),
                         jnp.int32)
    params = make(None).init(jax.random.PRNGKey(0), tokens)["params"]
    plain = make(None).apply({"params": params}, tokens)
    flash = make(make_attention_fn(causal=True, block_q=32, block_k=32)).apply(
        {"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(plain),
                               atol=1e-4, rtol=1e-4)


def test_attention_fn_rejects_dropout():
    q, k, v = _inputs(l=32)
    fn = make_attention_fn(causal=True)
    with pytest.raises(ValueError, match="dropout"):
        fn(q, k, v, None, jax.random.PRNGKey(0))


def test_attention_fn_rejects_padding_mask():
    """A non-causal adapter must not silently drop a padding mask."""
    q, k, v = _inputs(l=32)
    fn = make_attention_fn(causal=False)
    mask = jnp.ones((2, 1, 32, 32), bool)
    with pytest.raises(ValueError, match="mask"):
        fn(q, k, v, mask, None)
