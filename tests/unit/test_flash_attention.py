"""Flash-attention kernel: numeric parity with plain einsum attention.

Runs the Pallas interpreter on the CPU harness; on TPU the same code
compiles to the fused kernel.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from autodist_tpu.models.transformer import dot_product_attention
from autodist_tpu.ops import flash_attention, make_attention_fn


pytestmark = pytest.mark.slow

def _inputs(b=2, l=128, h=4, d=32, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(b, l, h, d) * 0.3, dtype)
    return mk(), mk(), mk()


def _reference(q, k, v, causal):
    mask = None
    if causal:
        l = q.shape[1]
        mask = jnp.tril(jnp.ones((l, l), bool))[None, None]
    return dot_product_attention(q, k, v, mask, dtype=jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _inputs()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_forward_uneven_blocks():
    """Sequence split into multiple q and k blocks of different sizes."""
    q, k, v = _inputs(l=96)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=16)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("l", [100, 127, 4])
def test_indivisible_seq_pads_and_masks(l, causal):
    """Arbitrary sequence lengths (incl. prime and sub-tile) are padded to
    a block multiple and masked — numerics must still match, forward and
    backward."""
    q, k, v = _inputs(l=l, d=16)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch at l={l}")


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    q, k, v = _inputs(l=64, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = _reference(q, k, v, causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_bfloat16_forward():
    q, k, v = _inputs(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    ref = _reference(q.astype(jnp.float32), k.astype(jnp.float32),
                     v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_transformer_integration():
    """TransformerLM with the flash attention_fn matches plain attention."""
    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)

    def make(attention_fn):
        cfg = TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
            mlp_dim=64, max_len=64, dropout_rate=0.0,
            attention_dropout_rate=0.0, causal=True, dtype=jnp.float32,
            attention_fn=attention_fn)
        return TransformerLM(cfg)

    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)),
                         jnp.int32)
    params = make(None).init(jax.random.PRNGKey(0), tokens)["params"]
    plain = make(None).apply({"params": params}, tokens)
    flash = make(make_attention_fn(causal=True, block_q=32, block_k=32)).apply(
        {"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(plain),
                               atol=1e-4, rtol=1e-4)


def test_attention_fn_rejects_dropout():
    q, k, v = _inputs(l=32)
    fn = make_attention_fn(causal=True)
    with pytest.raises(ValueError, match="dropout"):
        fn(q, k, v, None, jax.random.PRNGKey(0))


def test_attention_fn_rejects_padding_mask():
    """A non-causal adapter must not silently drop a padding mask."""
    q, k, v = _inputs(l=32)
    fn = make_attention_fn(causal=False)
    mask = jnp.ones((2, 1, 32, 32), bool)
    with pytest.raises(ValueError, match="mask"):
        fn(q, k, v, mask, None)


# --------------------------------------------------------------------------- #
# Measured tuning table (tools/flash_crossover.py --write)
# --------------------------------------------------------------------------- #
def test_tuning_table_resolution(tmp_path, monkeypatch):
    import json

    import importlib
    fa = importlib.import_module("autodist_tpu.ops.flash_attention")

    table = {"causal": {"crossover_len": 1024,
                        "blocks": {"512": 128, "2048": [256, 512]}},
             "noncausal": {"crossover_len": None,
                           "blocks": {"1024": 256}}}
    p = tmp_path / "flash_tuning.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("AUTODIST_TPU_FLASH_TUNING", str(p))
    fa.load_tuning(reload=True)
    try:
        # exact + nearest-below + nearest-above fallbacks
        assert fa.tuned_blocks(512, True) == (128, 128)
        assert fa.tuned_blocks(1024, True) == (128, 128)   # below: 512
        assert fa.tuned_blocks(4096, True) == (256, 512)   # below: 2048
        assert fa.tuned_blocks(256, True) == (128, 128)    # above: 512
        assert fa.tuned_blocks(1024, False) == (256, 256)
        # crossover semantics: measured-and-lost => False everywhere
        assert fa.flash_wins(512, True) is False
        assert fa.flash_wins(2048, True) is True
        assert fa.flash_wins(99999, False) is False        # null crossover
    finally:
        monkeypatch.delenv("AUTODIST_TPU_FLASH_TUNING")
        fa.load_tuning(reload=True)


def test_cpu_provenance_tuning_skipped_on_autoload(tmp_path, monkeypatch):
    """A table written by a CPU (interpret-mode) crossover run must not
    steer TPU kernel defaults: auto-load ignores backend=cpu tables; an
    explicit path still loads them."""
    import json

    import importlib
    fa = importlib.import_module("autodist_tpu.ops.flash_attention")

    table = {"causal": {"blocks": {"512": 512}}, "backend": "cpu"}
    p = tmp_path / "flash_tuning.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("AUTODIST_TPU_FLASH_TUNING", str(p))
    fa.load_tuning(reload=True)
    try:
        assert fa.tuned_blocks(512, True) == (fa.DEFAULT_BLOCK,
                                              fa.DEFAULT_BLOCK)
        assert fa.load_tuning(str(p))["causal"]["blocks"]["512"] == 512
    finally:
        monkeypatch.delenv("AUTODIST_TPU_FLASH_TUNING")
        fa.load_tuning(reload=True)


def test_tuning_absent_defaults(monkeypatch, tmp_path):
    import importlib
    fa = importlib.import_module("autodist_tpu.ops.flash_attention")

    monkeypatch.setenv("AUTODIST_TPU_FLASH_TUNING",
                       str(tmp_path / "missing.json"))
    fa.load_tuning(reload=True)
    try:
        assert fa.tuned_blocks(512, True) == (fa.DEFAULT_BLOCK,
                                              fa.DEFAULT_BLOCK)
        assert fa.flash_wins(512, True) is None
    finally:
        monkeypatch.delenv("AUTODIST_TPU_FLASH_TUNING")
        fa.load_tuning(reload=True)


def test_flash_attention_default_blocks_run():
    """block_q/block_k=None resolve through the table (or defaults) and
    the kernel still matches the reference einsum."""
    import numpy as np

    from autodist_tpu.ops.flash_attention import flash_attention

    r = np.random.RandomState(0)
    q, k, v = (jnp.asarray(r.randn(2, 64, 2, 16), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True)

    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(16)
    mask = jnp.tril(jnp.ones((64, 64), bool))
    p = jax.nn.softmax(jnp.where(mask[None, None], s, -1e30), -1)
    ref = jnp.einsum("bhlm,bmhd->blhd", p, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_crossover_tool_write_merges(tmp_path):
    """--write merges per-branch without clobbering the other branch."""
    import json
    import subprocess
    import sys

    out = tmp_path / "flash_tuning.json"
    # backend stamp matches the run below: same-provenance tables merge
    # (unstamped/cross-backend ones are discarded — separate test below)
    out.write_text(json.dumps(
        {"causal": {"crossover_len": 777, "blocks": {"512": 64}},
         "noncausal": {"blocks": {"999": 32}, "speedup": {"999": 2.0}},
         "backend": "cpu"}))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "tools/flash_crossover.py", "--seqs", "128",
         "--heads", "2", "--head-dim", "16", "--tokens", "256",
         "--blocks", "64", "--steps", "1", "--write", str(out)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    table = json.loads(out.read_text())
    # other branch untouched; same branch merged PER LENGTH
    assert table["causal"] == {"crossover_len": 777, "blocks": {"512": 64}}
    nb = table["noncausal"]
    assert nb["blocks"]["999"] == 32, "prior length must be preserved"
    assert "128" in nb["blocks"] and "128" in nb["speedup"]
    # crossover derived from per-length speedups (999 won at 2.0)
    assert nb["crossover_len"] in (128, 999)
    assert table["backend"] == "cpu", "written table must carry provenance"


def test_crossover_tool_write_discards_unstamped(tmp_path):
    """A tuning table without a backend stamp (or from another backend)
    has unknown provenance: --write starts fresh instead of merging, so
    stale entries can't masquerade under this run's stamp."""
    import json
    import subprocess
    import sys

    out = tmp_path / "flash_tuning.json"
    out.write_text(json.dumps(
        {"causal": {"crossover_len": 777, "blocks": {"512": 64}}}))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "tools/flash_crossover.py", "--seqs", "128",
         "--heads", "2", "--head-dim", "16", "--tokens", "256",
         "--blocks", "64", "--steps", "1", "--write", str(out)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr
    table = json.loads(out.read_text())
    assert "causal" not in table, "unstamped table must be discarded"
    assert "128" in table["noncausal"]["blocks"]


def test_flash_wins_prefers_per_length_speedups(tmp_path, monkeypatch):
    """The per-length speedup records (what --write persists) drive
    flash_wins by nearest measured length; a corrupt table degrades to
    'unmeasured', never a crash."""
    import importlib
    import json

    fa = importlib.import_module("autodist_tpu.ops.flash_attention")
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"noncausal": {
        "speedup": {"512": 0.88, "2048": 1.4},
        "blocks": {"512": 128, "2048": 256},
        "crossover_len": 2048}}))
    monkeypatch.setenv("AUTODIST_TPU_FLASH_TUNING", str(p))
    fa.load_tuning(reload=True)
    try:
        assert fa.flash_wins(512, False) is False
        assert fa.flash_wins(1024, False) is False   # nearest below: 512
        assert fa.flash_wins(2048, False) is True
        assert fa.flash_wins(8192, False) is True
        # corrupt table: wrong types everywhere -> graceful defaults
        p.write_text(json.dumps(["not", "a", "dict"]))
        fa.load_tuning(reload=True)
        assert fa.flash_wins(512, False) is None
        assert fa.tuned_blocks(512, False) == (fa.DEFAULT_BLOCK,
                                               fa.DEFAULT_BLOCK)
    finally:
        monkeypatch.delenv("AUTODIST_TPU_FLASH_TUNING")
        fa.load_tuning(reload=True)


def test_flash_bf16_inputs_match_einsum_reference():
    """bf16 q/k/v (the bench/crossover operating dtype): matmul inputs
    stay bf16 (full MXU rate) with fp32 accumulation + fp32 softmax —
    forward and grads match a reference that computes the same
    mixed-precision einsum attention."""
    r = np.random.RandomState(3)
    B, L, H, D = 2, 128, 2, 32
    q, k, v = (jnp.asarray(r.randn(B, L, H, D), jnp.bfloat16)
               for _ in range(3))

    def ref(q, k, v):
        s = jnp.einsum("blhd,bmhd->bhlm", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((L, L), bool))
        p = jax.nn.softmax(jnp.where(mask[None, None], s, -1e30), -1)
        return jnp.einsum("bhlm,bmhd->blhd", p.astype(jnp.bfloat16), v,
                          preferred_element_type=jnp.float32)

    out = flash_attention(q, k, v, causal=True).astype(jnp.float32)
    expected = ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=0.05, atol=0.02)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ref(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(e, np.float32),
            rtol=0.1, atol=0.05)
