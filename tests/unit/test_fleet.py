"""Fleet goldens: fault-tolerant multi-replica serving (ISSUE 15).

The robustness bar: a request decodes the exact same token stream
whether it runs alone on one engine, routed across a 2-replica fleet,
failed over mid-stream after a replica death, or raced by a hedge —
for greedy and seeded-sampled decode, across tp and KV layouts — with
every terminal state returning its paged KV blocks
(``free + used == total``, ``free == total`` once idle), every routing
decision a schema-gated ``kind="dispatch"`` record, and the ADT085+
fleet lint firing both ways.
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.serving import (FINISH_REASONS, ContinuousBatcher,
                                  FleetConfig, OverloadedError, Router,
                                  ServingEngine, ServingFleet)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V = 33          # odd: V % 2 != 0 exercises the vocab zero-pad path
MAX_LEN = 24
PROMPTS = [[1, 2, 3], [4, 5], [6], [7, 8, 9, 10]]
MAX_NEW = 6


def make_cfg():
    return TransformerConfig(
        vocab_size=V, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_len=MAX_LEN, dtype=jnp.float32,
        dropout_rate=0.0, attention_dropout_rate=0.0)


@pytest.fixture(scope="module")
def cfg():
    return make_cfg()


@pytest.fixture(scope="module")
def params(cfg):
    return make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params


def make_factory(cfg, params, tp=1, kv_layout="dense", temperature=0.0):
    def factory():
        return ServingEngine(
            cfg, params, tensor_parallel=tp, vocab_parallel=tp > 1,
            num_slots=2, max_len=MAX_LEN, prefill_len=16,
            decode_steps=3, kv_layout=kv_layout, kv_block_len=5,
            temperature=temperature, top_k=5 if temperature else 0)
    return factory


def run_alone(factory, reqs):
    """The golden: each request alone on one engine (sequentially —
    per-slot independence makes one engine's back-to-back runs exact
    run-alone streams, and it saves a compile per request)."""
    out = {}
    b = ContinuousBatcher(factory())
    for i, (prompt, seed) in enumerate(reqs):
        rid = b.submit(prompt, max_new_tokens=MAX_NEW, seed=seed)
        out[i] = b.run()[rid].tokens
    return out


def assert_zero_residency(fleet):
    acc = fleet.block_accounting()
    for name, (free, used, total) in acc.items():
        assert used == 0 and free == total, (name, acc)


# --------------------------------------------------------------------- #
# parity goldens: run-alone == routed == failover-mid-stream
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("tp,kv_layout", [
    (1, "dense"), (1, "paged"), (2, "dense"), (2, "paged")])
def test_fleet_parity_routed_and_failover_greedy(cfg, params, tp,
                                                 kv_layout):
    """Greedy decode is token-for-token identical run-alone, routed
    across 2 replicas, and failed over mid-stream after a replica
    crash — with zero block residency at the end of each run."""
    factory = make_factory(cfg, params, tp=tp, kv_layout=kv_layout)
    reqs = [(p, 0) for p in PROMPTS]
    golden = run_alone(factory, reqs)

    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p, _ in reqs]
    done = router.run()
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i], (i, done[rid])
    assert_zero_residency(fleet)

    fleet2 = ServingFleet(factory, replicas=2)
    router2 = Router(fleet2)
    rids2 = [router2.submit(p, max_new_tokens=MAX_NEW) for p, _ in reqs]
    router2.step()   # requests mid-stream
    fleet2.inject("replica-0", "crash")
    done2 = router2.run()
    failovers = 0
    for i, rid in enumerate(rids2):
        assert done2[rid].tokens == golden[i], (i, done2[rid])
        failovers += done2[rid].failovers
    assert failovers >= 1, "the crash never exercised the failover path"
    assert_zero_residency(fleet2)
    states = {(r.name, r.state) for r in fleet2.replicas}
    assert ("replica-0", "replaced") in states   # lifecycle completed


@pytest.mark.parametrize("tp,kv_layout", [(1, "paged"), (2, "dense")])
def test_fleet_parity_sampled_seeded(cfg, params, tp, kv_layout):
    """Seeded sampling keeps the same contract: the gumbel keys fold
    (request seed, context length, vocab row), so a failover
    re-prefill of prompt + emitted continues the IDENTICAL stream —
    the position-keyed draw is re-dispatch-invariant."""
    factory = make_factory(cfg, params, tp=tp, kv_layout=kv_layout,
                           temperature=0.8)
    reqs = [(p, 100 + i) for i, p in enumerate(PROMPTS[:3])]
    golden = run_alone(factory, reqs)

    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW, seed=s)
            for p, s in reqs]
    router.step()
    fleet.inject("replica-0", "crash")
    done = router.run()
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i], (i, done[rid])
    assert_zero_residency(fleet)


def test_hedged_request_loser_cancelled(cfg, params):
    """A straggler replica's request is hedged onto a healthy replica;
    the first completion wins, the loser is cancelled (blocks freed
    the same round), and the stream equals run-alone."""
    factory = make_factory(cfg, params, kv_layout="paged")
    golden = run_alone(factory, [(PROMPTS[0], 0)])
    fleet = ServingFleet(factory, replicas=2,
                         config=FleetConfig(hedge_timeout_s=0.02))
    router = Router(fleet)
    fleet.inject("replica-0", "slow", duration_s=5.0)
    rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
    done = router.run()
    comp = done[rid]
    assert comp.tokens == golden[0]
    assert comp.hedged and comp.hedge_won
    assert comp.replica == "replica-1"
    # the loser's dispatch was withdrawn on the slow replica
    slow = fleet.replicas[0]
    cancelled = [c for c in slow.batcher.completions.values()
                 if c.finish_reason == "cancelled"]
    assert cancelled, "the hedge loser was never cancelled"
    assert_zero_residency(fleet)


@pytest.mark.slow
def test_drain_races_in_flight_hedge_both_orderings(cfg, params):
    """Draining while a hedge is mid-flight, in both orders — drain the
    straggler that still holds the losing dispatch, and drain the
    healthy replica that holds the winning one.  Either way the client
    stream equals run-alone and every replica's pool settles back to
    ``free + used == total`` with zero residency: a drain sweep must
    not strand the hedge sibling's dispatch or its KV blocks."""
    factory = make_factory(cfg, params, kv_layout="paged")
    golden = run_alone(factory, [(PROMPTS[0], 0)])
    for victim in ("replica-0", "replica-1"):
        fleet = ServingFleet(factory, replicas=2,
                             config=FleetConfig(hedge_timeout_s=0.02))
        router = Router(fleet)
        fleet.inject("replica-0", "slow", duration_s=0.5)
        rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
        deadline = time.monotonic() + 10.0
        while rid in router._open \
                and len(router._open[rid].dispatches) < 2:
            assert time.monotonic() < deadline, \
                "hedge never fired against the straggler"
            router.step()
        assert rid in router._open, \
            "request completed before the drain could race the hedge"
        router.drain_replica(victim)
        done = router.run()
        comp = done[rid]
        assert comp.tokens == golden[0], (victim, comp.tokens)
        assert comp.hedged
        assert_zero_residency(fleet)
        for name, (free, used, total) in fleet.block_accounting().items():
            assert free + used == total, (victim, name, free, used, total)


def test_hang_detected_by_heartbeat_and_failed_over(cfg, params):
    """A hung replica (no beats, no progress) is declared dead by the
    reused HeartbeatMonitor freshness check and its requests fail
    over — the training plane's detection semantics on the serving
    plane."""
    factory = make_factory(cfg, params)
    golden = run_alone(factory, [(p, 0) for p in PROMPTS])
    fleet = ServingFleet(
        factory, replicas=2,
        config=FleetConfig(heartbeat_interval_s=0.02,
                           heartbeat_timeout_s=0.25,
                           heartbeat_startup_grace_s=0.25,
                           max_replacements=1))
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    router.step()
    fleet.inject("replica-0", "hang")
    done = router.run()
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i]
    dead = fleet.replicas[0]
    assert dead.declared_fault == "replica_hang"


def test_scheduler_idle_gap_is_not_a_replica_hang(cfg, params):
    """Beats only advance while the scheduler steps: a caller-side
    idle gap longer than the heartbeat timeout must reset the
    freshness windows, never mass-declare healthy replicas dead."""
    factory = make_factory(cfg, params)
    fleet = ServingFleet(
        factory, replicas=2,
        config=FleetConfig(heartbeat_interval_s=0.02,
                           heartbeat_timeout_s=0.1,
                           heartbeat_startup_grace_s=0.1))
    router = Router(fleet)
    rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
    router.run()
    time.sleep(0.3)            # idle: no steps, no beats, no polls
    rid2 = router.submit(PROMPTS[1], max_new_tokens=MAX_NEW)
    done = router.run()
    assert done[rid2].finish_reason == "max_tokens"
    assert all(r.state == "admitting" for r in fleet.replicas)
    assert router.completions[rid].failovers == 0


def test_single_replica_drain_roll_keeps_drain_provenance(cfg, params):
    """A drain re-home delayed by a replica-less gap (single-replica
    rolling restart) is still recorded reason="drain" once the
    successor spawns — the drain sibling of the failover_from fix."""
    tel = telemetry.reset()
    tel.enabled = True
    try:
        factory = make_factory(cfg, params)
        golden = run_alone(factory, [(PROMPTS[0], 0)])
        fleet = ServingFleet(factory, replicas=1)
        router = Router(fleet)
        # queued behind a full slot set so the dispatch is still in
        # the replica queue when the drain lands
        rids = [router.submit(p, max_new_tokens=MAX_NEW)
                for p in PROMPTS[:3]]
        router.step()
        fleet.drain("replica-0", replace=True)
        done = router.run()
        assert done[rids[0]].tokens == golden[0]
        dispatches = [r for r in tel.step_records()
                      if r.get("kind") == "dispatch"]
        assert any(r["reason"] == "drain" for r in dispatches)
        assert not any(r["reason"] == "failover" for r in dispatches)
    finally:
        telemetry.reset()


def test_slow_replica_is_not_declared_dead(cfg, params):
    """A straggler keeps beating: the health check must never declare
    it (hedging's territory) — the slow-vs-hang distinction."""
    factory = make_factory(cfg, params)
    fleet = ServingFleet(
        factory, replicas=2,
        config=FleetConfig(heartbeat_interval_s=0.02,
                           heartbeat_timeout_s=0.15,
                           heartbeat_startup_grace_s=0.15))
    fleet.inject("replica-0", "slow", duration_s=0.4)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.6:
        for r in fleet.live:
            r.step()
        fleet.poll_health()
        time.sleep(0.01)
    assert fleet.replicas[0].state == "admitting"
    assert fleet.replicas[0]._fault is None   # resumed


def test_replacement_budget_escalates_to_shrunk_fleet(cfg, params):
    """Beyond the replacement budget the fleet continues shrunk
    (escalated, recorded) — and still completes every request."""
    factory = make_factory(cfg, params)
    golden = run_alone(factory, [(p, 0) for p in PROMPTS])
    fleet = ServingFleet(factory, replicas=2,
                         config=FleetConfig(max_replacements=0))
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    router.step()
    fleet.inject("replica-0", "crash")
    done = router.run()
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i]
    assert fleet.escalated
    assert len(fleet.live) == 1
    # an escalated (never-rebuilt) replica reports "dead", not
    # "replaced" — state printouts must show the shrunk capacity
    assert fleet.replicas[0].state == "dead"


def test_fleet_with_no_survivors_sheds_instead_of_hanging(cfg, params):
    """Every replica dead + budget spent: open requests complete
    exactly once as "shed" (coded, resubmittable) — run() terminates."""
    factory = make_factory(cfg, params)
    fleet = ServingFleet(factory, replicas=1,
                         config=FleetConfig(max_replacements=0))
    router = Router(fleet)
    rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
    fleet.inject("replica-0", "crash")
    done = router.run()
    assert done[rid].finish_reason == "shed"
    assert set(router.completions) == {rid}


def test_failover_across_replicaless_gap_is_still_recorded(cfg, params):
    """A single-replica fleet whose only replica crashes: the re-home
    waits for the replacement, and the eventual dispatch is STILL a
    reason="failover" record naming the dead source — a delayed
    failover must not be relabeled a plain route."""
    tel = telemetry.reset()
    tel.enabled = True
    try:
        factory = make_factory(cfg, params)
        golden = run_alone(factory, [(PROMPTS[0], 0)])
        fleet = ServingFleet(factory, replicas=1,
                             config=FleetConfig(max_replacements=1))
        router = Router(fleet)
        rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
        router.step()
        fleet.inject("replica-0", "crash")
        done = router.run()
        assert done[rid].tokens == golden[0]
        assert done[rid].failovers == 1
        dispatches = [r for r in tel.step_records()
                      if r.get("kind") == "dispatch"]
        failovers = [r for r in dispatches if r["reason"] == "failover"]
        assert len(failovers) == 1
        assert failovers[0]["from_replica"] == "replica-0"
    finally:
        telemetry.reset()


def test_drain_replace_rolls_the_replica(cfg, params):
    """drain(replace=True): the rolling-restart shape — the drained
    replica retires and a fresh incarnation takes its name, without
    touching the failure-replacement budget."""
    factory = make_factory(cfg, params)
    fleet = ServingFleet(factory, replicas=2,
                         config=FleetConfig(max_replacements=0))
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    fleet.drain("replica-0", replace=True)
    router.run()
    states = [(r.name, r.incarnation, r.state) for r in fleet.replicas]
    assert ("replica-0", 0, "replaced") in states
    assert ("replica-0", 1, "admitting") in states
    assert not fleet.escalated
    # the fresh incarnation takes traffic again
    rid = router.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
    assert router.run()[rid].tokens   # served, not shed
    assert len(fleet.admitting) == 2


def test_drain_rehomes_queued_and_finishes_in_flight(cfg, params):
    """Draining a replica: queued dispatches move (reason="drain"),
    in-flight ones finish in place, the drained replica retires dead,
    and every stream equals run-alone."""
    factory = make_factory(cfg, params)
    reqs = [(p, 0) for p in PROMPTS * 2]
    golden = run_alone(factory, reqs)
    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p, _ in reqs]
    router.drain_replica("replica-0")
    done = router.run()
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i]
    assert fleet.replicas[0].state == "dead"
    with pytest.raises(ValueError, match="no admitting replica"):
        fleet.drain("replica-0")


# --------------------------------------------------------------------- #
# the block-leak audit (the deadline/shed/cancel terminal states)
# --------------------------------------------------------------------- #
def _paged_engine(cfg, params, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", MAX_LEN)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("decode_steps", 3)
    return ServingEngine(cfg, params, kv_layout="paged", kv_block_len=5,
                         **kw)


def test_deadline_expiry_of_admitted_request_returns_blocks(cfg, params):
    """The PR 14 gap: deadline expiry of an ADMITTED request must
    release its reservation like every other terminal state."""
    eng = _paged_engine(cfg, params)
    b = ContinuousBatcher(eng)
    rid = b.submit([1, 2, 3], max_new_tokens=20, deadline_s=0.05)
    b.step()   # admitted: blocks reserved
    free, used, total = eng.block_accounting()
    assert used > 0 and free + used == total
    time.sleep(0.1)
    b.run()
    assert b.completions[rid].finish_reason == "deadline_exceeded"
    assert eng.block_accounting() == (total, 0, total)


def test_every_terminal_state_restores_block_accounting(cfg, params):
    """free + used == total after queued expiry, shedding, drain (both
    modes), and cancel (queued + in-flight)."""
    eng = _paged_engine(cfg, params)
    b = ContinuousBatcher(eng, max_queue=2)
    total = eng.kv_num_blocks
    # queued deadline expiry (never admitted: no reservation to leak)
    r1 = b.submit([1], max_new_tokens=4, deadline_s=0.01)
    r2 = b.submit([2], max_new_tokens=4)
    with pytest.raises(OverloadedError):   # shed at the queue bound
        b.submit([3], max_new_tokens=4)
    time.sleep(0.05)
    b.run()
    assert b.completions[r1].finish_reason == "deadline_exceeded"
    assert b.completions[r2].finish_reason == "max_tokens"
    assert eng.block_accounting() == (total, 0, total)
    # cancel: queued and in-flight
    r3 = b.submit([1, 2], max_new_tokens=8)
    assert b.cancel(r3)                     # still queued
    assert b.completions[r3].finish_reason == "cancelled"
    assert not b.cancel(r3)                 # not live anymore
    r4 = b.submit([1, 2], max_new_tokens=8)
    b.step()                                # admitted
    assert b.cancel(r4)
    assert b.completions[r4].finish_reason == "cancelled"
    assert len(b.completions[r4].tokens) >= 1   # kept what it had
    assert eng.block_accounting() == (total, 0, total)
    # drain with an in-flight cut
    r5 = b.submit([3], max_new_tokens=8)
    b.step()
    out = b.drain(finish_in_flight=False)
    assert out[r5].finish_reason == "drained"
    assert eng.block_accounting() == (total, 0, total)
    assert "cancelled" in FINISH_REASONS


def test_prefill_failure_releases_reservations_and_requeues(cfg, params):
    """The crash-path bugfix: an engine dying mid-prefill must not
    strand the blocks reserved for the requests it was admitting —
    they are released and the requests go back to the queue head."""
    eng = _paged_engine(cfg, params)
    b = ContinuousBatcher(eng)
    total = eng.kv_num_blocks
    rid = b.submit([1, 2, 3], max_new_tokens=4)
    orig = eng.prefill
    calls = {"n": 0}

    def failing_prefill(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected engine death")
        return orig(*a, **kw)

    eng.prefill = failing_prefill
    with pytest.raises(RuntimeError, match="injected engine death"):
        b.step()
    assert eng.block_accounting() == (total, 0, total)
    assert b.queue_depth == 1               # back at the head
    out = b.run()                           # the engine healed: serve it
    assert out[rid].finish_reason == "max_tokens"
    assert eng.block_accounting() == (total, 0, total)


# --------------------------------------------------------------------- #
# dispatch telemetry: schema gate + fleet report section
# --------------------------------------------------------------------- #
def _report_tools():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    return telemetry_report


def test_dispatch_records_schema_and_failover_pairing(cfg, params,
                                                      tmp_path):
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path), enabled=True)
    try:
        factory = make_factory(cfg, params, kv_layout="paged")
        fleet = ServingFleet(factory, replicas=2)
        router = Router(fleet)
        rids = [router.submit(p, max_new_tokens=MAX_NEW)
                for p in PROMPTS]
        router.step()
        fleet.inject("replica-0", "crash")
        router.run()
        telemetry.flush()
    finally:
        telemetry.reset()
    tr = _report_tools()
    assert tr.check_schema(str(tmp_path)) == []
    with open(os.path.join(tmp_path, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    dispatches = [r for r in recs if r.get("kind") == "dispatch"]
    assert {r["request"] for r in dispatches
            if r["reason"] == "route"} == set(rids)
    failovers = [r for r in dispatches if r["reason"] == "failover"]
    assert failovers and all(r["re_emitted"] == 0 for r in dispatches)
    assert all(r["from_replica"] == "replica-0" for r in failovers)
    md = tr.render(str(tmp_path))
    assert "## fleet" in md and "failover" in md
    assert "replica-0" in md   # the per-replica queue-depth rows

    # the gate fires on: a re-emitted token, an unknown reason, and a
    # failover with no paired fault record
    base = [r for r in recs]
    doctor = tmp_path / "doctored"

    def write(mods):
        doctor.mkdir(exist_ok=True)
        with open(doctor / "metrics.jsonl", "w") as f:
            for r in mods:
                f.write(json.dumps(r) + "\n")
        return tr.check_schema(str(doctor))

    bad = [dict(r) for r in base]
    for r in bad:
        if r.get("kind") == "dispatch":
            r["re_emitted"] = 2
    assert any("re_emitted" in p for p in write(bad))
    bad = [dict(r) for r in base]
    for r in bad:
        if r.get("kind") == "dispatch":
            r["reason"] = "vibes"
    assert any("unknown dispatch reason" in p for p in write(bad))
    orphan = [dict(r) for r in base if r.get("kind") != "fault"]
    assert any("unaudited failover" in p for p in write(orphan))


# --------------------------------------------------------------------- #
# the fleet objective (replicas x tp x kv_layout across ICI/DCN)
# --------------------------------------------------------------------- #
def _serving_trainable():
    return make_pipeline_lm_trainable(
        make_cfg(), optax.sgd(0.1), jax.random.PRNGKey(0))


def test_fleet_objective_elects_replicas_and_gates_tp(cfg):
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import (CostModel,
                                        default_fleet_candidates,
                                        rank_serving)

    trainable = _serving_trainable()
    spec = ResourceSpec({"topology": {"num_devices": 8,
                                      "num_slices": 2}})
    ranked = rank_serving(trainable, spec, objective="fleet",
                          max_len=MAX_LEN, mean_request_len=8)
    assert ranked
    best_cand, best_cost = ranked[0]
    # capacity scales with replicas at equal latency: the fleet
    # objective fills the device budget with replicas
    assert best_cand.get("replicas", 1) > 1
    # tp within a slice's ICI, everywhere in the scored set
    assert all(c["tensor_parallel"] <= 4 for c, _ in ranked)
    # fleet_score monotone in replicas at fixed (tp, layout)
    cm = CostModel(spec)
    one = cm.decode_cost(trainable, {"tensor_parallel": 1},
                         max_len=MAX_LEN, mean_request_len=8)
    two = cm.decode_cost(trainable,
                         {"tensor_parallel": 1, "replicas": 2},
                         max_len=MAX_LEN, mean_request_len=8)
    assert two.fleet_score < one.fleet_score
    # replicas are PRICED across DCN: a fleet spanning slices carries
    # a dispatch term, a single-slice fleet does not
    wide = cm.decode_cost(trainable,
                          {"tensor_parallel": 2, "replicas": 4},
                          max_len=MAX_LEN, mean_request_len=8)
    assert wide.dispatch_time_s > 0
    assert two.dispatch_time_s == 0
    # ...and tp is FORBIDDEN across DCN (the ADT088 contract at
    # pricing time), as is overflowing the device budget
    with pytest.raises(ValueError, match="within a slice"):
        cm.decode_cost(trainable, {"tensor_parallel": 8},
                       max_len=MAX_LEN)
    with pytest.raises(ValueError, match="needs"):
        cm.decode_cost(trainable,
                       {"tensor_parallel": 4, "replicas": 4},
                       max_len=MAX_LEN)
    # the candidate zoo respects both bounds by construction
    for cand in default_fleet_candidates(8, num_slices=2):
        assert cand["tensor_parallel"] <= 4
        assert cand.get("replicas", 1) * cand["tensor_parallel"] <= 8
    with pytest.raises(ValueError, match="fleet"):
        rank_serving(trainable, spec, objective="warp")


def test_fleet_lint_fires_both_ways():
    from autodist_tpu.analysis import lint_fleet
    from autodist_tpu.analysis.mutations import run_mutations
    from autodist_tpu.resource import ResourceSpec

    results = run_mutations(kinds=["fleet"])
    assert {r["code"] for r in results} == {"ADT085", "ADT086",
                                            "ADT087", "ADT088"}
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad
    # the shipped default config is clean, and the shared ADT081
    # heartbeat rule fires on a fleet config too
    assert lint_fleet(FleetConfig()).ok
    report = lint_fleet(FleetConfig(heartbeat_interval_s=5.0,
                                    heartbeat_timeout_s=1.0))
    assert "ADT081" in report.codes()
    spec = ResourceSpec({"topology": {"num_devices": 2}})
    report = lint_fleet({"replicas": 4, "tensor_parallel": 1},
                        resource_spec=spec)
    assert "ADT086" in report.codes()


def test_fleet_describe_lints_through_the_object(cfg, params):
    fleet = ServingFleet(make_factory(cfg, params), replicas=2,
                         warm=False)
    d = fleet.describe()
    assert d["tensor_parallel"] == 1 and d["has_engine_source"]
    from autodist_tpu.resource import ResourceSpec

    assert fleet.lint(ResourceSpec(
        {"topology": {"num_devices": 2}})).ok
    report = fleet.lint(ResourceSpec({"topology": {"num_devices": 1}}))
    assert "ADT086" in report.codes()
