"""Gradient accumulation: microbatch scan == full-batch numerics.

With equal-size microbatches, the mean of microbatch-mean gradients
equals the full-batch mean gradient, so accumulation must reproduce the
plain run exactly — on both lowering paths.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, GradAccumulation,
                          PartitionedPS, Trainable)
from autodist_tpu.strategy.gspmd_builders import Sharded

from tests.unit.test_end_to_end import (make_batch, make_trainable,
                                        single_device_reference)


@pytest.mark.parametrize("inner", [AllReduce, PartitionedPS, Sharded],
                         ids=["AllReduce", "PartitionedPS", "gspmd-Sharded"])
def test_accumulation_matches_full_batch(inner):
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist({}, GradAccumulation(inner(), 2)).build(
        make_trainable())
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_accumulation_survives_serialization():
    t = make_trainable()
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.ir import Strategy

    s = GradAccumulation(AllReduce(), 4).build(t, ResourceSpec({}))
    assert s.graph_config.accum_steps == 4
    s2 = Strategy.from_json(s.to_json())
    assert s2.graph_config.accum_steps == 4


def test_accumulation_with_scalar_feed_and_metrics():
    params = {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(p, batch):
        l = jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2) * batch["s"]
        return l, {"hits": jnp.sum(batch["y"] > 0).astype(jnp.int32)}

    t = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))
    runner = AutoDist({}, GradAccumulation(AllReduce(), 2)).build(t)
    r = np.random.RandomState(0)
    b = {"x": r.randn(16, 4).astype(np.float32),
         "y": r.randn(16).astype(np.float32),
         "s": np.float32(1.0)}
    m = runner.step(b)
    # int metric: summed over microbatches AND replicas = global count.
    assert int(np.asarray(m["hits"])) == int((b["y"] > 0).sum())


def test_accumulation_rejects_indivisible_batch():
    runner = AutoDist({}, GradAccumulation(AllReduce(), 3)).build(
        make_trainable())
    with pytest.raises(ValueError, match="divisible|accum"):
        runner.step(make_batch(0))  # 16/8 devices = 2 per device, 2 % 3


def test_accumulation_bool_metric_ors_and_create_by_name():
    from autodist_tpu.strategy import builders

    b = builders.create("GradAccumulation", builder="AllReduce", steps=2)
    assert isinstance(b, GradAccumulation)

    params = {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2), {
            "big_seen": jnp.any(jnp.abs(batch["y"]) > 1.0)}

    t = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))
    runner = AutoDist({}, GradAccumulation(AllReduce(), 2)).build(t)
    y = np.zeros(16, np.float32)
    y[0] = 5.0  # only the FIRST microbatch of one device sees it
    m = runner.step({"x": np.ones((16, 4), np.float32), "y": y})
    assert bool(np.asarray(m["big_seen"]))  # OR across microbatches
