"""GSPMD lowering tests: tensor-parallel and FSDP-sharded strategies must
match single-device numerics, and param shardings must actually land on
the declared mesh axes."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist, Trainable
from autodist_tpu.strategy.gspmd_builders import (FSDPSharded, Sharded,
                                                  TensorParallel)

from tests.unit.test_end_to_end import (make_batch, make_trainable,
                                        single_device_reference)


pytestmark = pytest.mark.slow

def test_sharded_dp_matches_single_device():
    trainable = make_trainable()
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist({}, Sharded()).build(trainable)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_sharded_rules_place_params():
    trainable = make_trainable()
    rules = [(r"dense/w$", ["model", None])]
    ad = AutoDist({"mesh": {"data": 4, "model": 2}}, Sharded(rules))
    runner = ad.build(trainable)
    w = runner.state["params"]["dense"]["w"]
    assert w.sharding.spec == P("model", None)
    b = runner.state["params"]["dense"]["b"]
    assert b.sharding.spec == P()
    # training still works and matches single-device numerics
    batches = [make_batch(s) for s in range(2)]
    expected = single_device_reference(make_trainable(), batches)
    for bt in batches:
        runner.step(bt)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_fsdp_sharded_matches():
    trainable = make_trainable()
    batches = [make_batch(s) for s in range(2)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist({}, FSDPSharded(min_size=1)).build(trainable)
    # dense/w dim0=6 not divisible by 8: lowering replicates it (warns)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_tensor_parallel_transformer():
    """TP over a 2x4 data x model mesh on the bundled transformer."""
    from autodist_tpu import models

    cfg = models.TransformerConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        mlp_dim=64, max_len=16, dtype=jnp.float32, dropout_rate=0.0)
    model = models.TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((4, 8), jnp.int32)
    params = model.init({"params": rng}, tokens)["params"]

    def loss(p, extra, batch, step_rng):
        logits = model.apply({"params": p}, batch["x"], deterministic=True)
        l, metrics = models.lm_loss_head(logits, batch)
        return l, extra, dict(metrics, loss=l)

    trainable = Trainable(loss, params, optax.adam(1e-2), name="lm_tp")
    ad = AutoDist({"mesh": {"data": 2, "model": 4}}, TensorParallel())
    runner = ad.build(trainable)

    # qkv kernels must be sharded on the model axis
    qkv = runner.state["params"]["encoder"]["layer_0"]["attention"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, None, "model", None)
    wi = runner.state["params"]["encoder"]["layer_0"]["mlp"]["wi"]["kernel"]
    assert wi.sharding.spec == P(None, "model")

    r = np.random.RandomState(0)
    xs = [r.randint(0, 128, (8, 8)).astype(np.int32) for _ in range(4)]
    batches = [{"x": x, "y": x} for x in xs]  # learnable copy task
    losses = [float(runner.step(b)["loss"]) for b in batches]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # TP numerics must match pure-DP numerics on the same model
    from autodist_tpu import AllReduce
    trainable2 = Trainable(loss, params, optax.adam(1e-2), name="lm_dp")
    runner2 = AutoDist({}, AllReduce()).build(trainable2)
    losses2 = [float(runner2.step(b)["loss"]) for b in batches]
    np.testing.assert_allclose(losses, losses2, rtol=5e-4, atol=5e-5)


def test_tensor_parallel_golden_params_vs_single_device():
    """dp x tp over a 2x4 mesh must reproduce a *plain optax loop on one
    device* — post-training parameter values, not just losses (the
    round-2 verdict's missing golden bar for the GSPMD path)."""
    from autodist_tpu import models

    cfg = models.TransformerConfig(
        vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        attention_dropout_rate=0.0)
    model = models.TransformerLM(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((4, 8), jnp.int32))["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"], deterministic=True)
        l, _ = models.lm_loss_head(logits, batch)
        return l

    r = np.random.RandomState(0)
    xs = [r.randint(0, 64, (8, 8)).astype(np.int32) for _ in range(3)]
    batches = [{"x": x, "y": x} for x in xs]

    # Ground truth: plain optax, full batch, one device.  sgd keeps the
    # comparison linear in fp noise (adam's m/sqrt(v) amplifies it).
    ref = jax.tree.map(jnp.asarray, params)
    opt = optax.sgd(0.5)
    opt_state = opt.init(ref)
    for b in batches:
        grads = jax.grad(loss_fn)(ref, jax.tree.map(jnp.asarray, b))
        updates, opt_state = opt.update(grads, opt_state, ref)
        ref = optax.apply_updates(ref, updates)

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.5))
    runner = AutoDist({"mesh": {"data": 2, "model": 4}},
                      TensorParallel()).build(trainable)
    for b in batches:
        runner.step(b)

    got = runner.get_params()
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-5, atol=2e-6),
        got, jax.device_get(ref))


def test_scalar_feed_duplicates():
    """Scalars in the batch replicate to every device (the reference
    duplicated non-polymorphic feeds, remapper.py:81-123)."""
    import optax as _optax
    from autodist_tpu import AllReduce

    params = {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2) \
            * batch["scale"]

    r = np.random.RandomState(0)
    b = {"x": r.randn(16, 4).astype(np.float32),
         "y": r.randn(16).astype(np.float32)}

    def loss_at(builder, scale):
        t = Trainable.from_loss_fn(loss_fn, dict(params), _optax.sgd(0.1))
        runner = AutoDist({"mesh": {"data": 2, "model": 4}}
                          if builder is TensorParallel else {},
                          builder()).build(t)
        m = runner.step(dict(b, scale=np.float32(scale)))
        return float(np.asarray(m["loss"]))

    # The scalar's VALUE must reach every replica, on both lowerings.
    for builder in (AllReduce, Sharded):
        l1 = loss_at(builder, 1.0)
        l05 = loss_at(builder, 0.5)
        np.testing.assert_allclose(l05, 0.5 * l1, rtol=1e-6,
                                   err_msg=builder.__name__)
