"""GSPMD lowering tests: tensor-parallel and FSDP-sharded strategies must
match single-device numerics, and param shardings must actually land on
the declared mesh axes."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist, Trainable
from autodist_tpu.strategy.gspmd_builders import (FSDPSharded, Sharded,
                                                  TensorParallel)

from tests.unit.test_end_to_end import (make_batch, make_trainable,
                                        single_device_reference)


def test_sharded_dp_matches_single_device():
    trainable = make_trainable()
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist({}, Sharded()).build(trainable)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_sharded_rules_place_params():
    trainable = make_trainable()
    rules = [(r"dense/w$", ["model", None])]
    ad = AutoDist({"mesh": {"data": 4, "model": 2}}, Sharded(rules))
    runner = ad.build(trainable)
    w = runner.state["params"]["dense"]["w"]
    assert w.sharding.spec == P("model", None)
    b = runner.state["params"]["dense"]["b"]
    assert b.sharding.spec == P()
    # training still works and matches single-device numerics
    batches = [make_batch(s) for s in range(2)]
    expected = single_device_reference(make_trainable(), batches)
    for bt in batches:
        runner.step(bt)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_fsdp_sharded_matches():
    trainable = make_trainable()
    batches = [make_batch(s) for s in range(2)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist({}, FSDPSharded(min_size=1)).build(trainable)
    # dense/w dim0=6 not divisible by 8: lowering replicates it (warns)
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, rtol=2e-5, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_tensor_parallel_transformer():
    """TP over a 2x4 data x model mesh on the bundled transformer."""
    from autodist_tpu import models

    cfg = models.TransformerConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        mlp_dim=64, max_len=16, dtype=jnp.float32, dropout_rate=0.0)
    model = models.TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((4, 8), jnp.int32)
    params = model.init({"params": rng}, tokens)["params"]

    def loss(p, extra, batch, step_rng):
        logits = model.apply({"params": p}, batch["x"], deterministic=True)
        l, metrics = models.lm_loss_head(logits, batch)
        return l, extra, dict(metrics, loss=l)

    trainable = Trainable(loss, params, optax.adam(1e-2), name="lm_tp")
    ad = AutoDist({"mesh": {"data": 2, "model": 4}}, TensorParallel())
    runner = ad.build(trainable)

    # qkv kernels must be sharded on the model axis
    qkv = runner.state["params"]["encoder"]["layer_0"]["attention"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, None, "model", None)
    wi = runner.state["params"]["encoder"]["layer_0"]["mlp"]["wi"]["kernel"]
    assert wi.sharding.spec == P(None, "model")

    r = np.random.RandomState(0)
    xs = [r.randint(0, 128, (8, 8)).astype(np.int32) for _ in range(4)]
    batches = [{"x": x, "y": x} for x in xs]  # learnable copy task
    losses = [float(runner.step(b)["loss"]) for b in batches]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # TP numerics must match pure-DP numerics on the same model
    from autodist_tpu import AllReduce
    trainable2 = Trainable(loss, params, optax.adam(1e-2), name="lm_dp")
    runner2 = AutoDist({}, AllReduce()).build(trainable2)
    losses2 = [float(runner2.step(b)["loss"]) for b in batches]
    np.testing.assert_allclose(losses, losses2, rtol=5e-4, atol=5e-5)
