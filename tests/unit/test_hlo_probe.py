"""HLO-structural falsifiability (tools/hlo_probe.py): the perf claims
the VERDICT demanded silicon-free proof for, asserted as collective
counts/kinds in compiled HLO on the simulated CPU mesh.

Tier-1 by design: a reintroduced single-replica all-reduce, a silently
re-fused monolithic TP all-reduce (the collective-matmul decomposition
undone by an XLA combiner pass or a code regression), or an unrolled
steps-per-loop scan each fail CI here, on CPU, before any hardware
window."""
import json

from tools.hlo_probe import (buffers_with_dim, buffers_with_dim_repeated,
                             collective_counts, collective_wire,
                             convert_counts, dynamic_update_slices,
                             entry_signature, large_copies_with_dim, main,
                             narrowed_collective_counts,
                             nonscalar_all_reduces,
                             probe_collective_matmul, probe_decode,
                             probe_pipeline_tp, probe_quantized,
                             probe_single_replica, probe_steps_per_loop,
                             probe_vocab_parallel, probe_zero3)


def test_collective_counts_parses_hlo_idioms():
    text = """
  %all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %p), replica_groups={{0,1}}
  %ag = (f32[4]{0}, f32[4]{0}) all-gather-start(f32[2]{0} %x), dimensions={0}
  %cp = f32[8]{0} collective-permute(f32[8]{0} %y), source_target_pairs={{0,1}}
  %fusion.all-reduce-ish = f32[] fusion(f32[] %z), kind=kLoop
"""
    counts = collective_counts(text)
    assert counts["all-reduce"] == 1
    assert counts["all-gather"] == 1
    assert counts["collective-permute"] == 1
    assert counts["reduce-scatter"] == 0 and counts["all-to-all"] == 0


def test_steps_per_loop_is_one_fused_dispatch():
    """k fused steps: one module, a while loop, and the one-step
    program's collective counts (scan body not unrolled)."""
    report = probe_steps_per_loop(k=4)
    assert report["fused_loop"]
    assert report["collectives_k_steps"] == report["collectives_one_step"]
    assert report["collectives_one_step"]["all-reduce"] >= 1


def test_single_replica_bypass_emits_zero_all_reduce():
    report = probe_single_replica()
    assert report["collectives"]["all-reduce"] == 0
    assert sum(report["collectives"].values()) == 0


def test_pipeline_tp_emits_model_axis_collectives():
    """tensor_parallel=2 adds the per-stage Megatron activation
    all-reduces (>= 4: out-proj + wo, forward + backward) on top of the
    tp=1 pipeline program, which itself carries the ppermute ring."""
    report = probe_pipeline_tp()
    assert report["collectives_tp1"]["collective-permute"] > 0
    assert report["collectives_tp2"]["collective-permute"] > 0
    assert report["model_axis_all_reduces"] >= 4


def test_collective_matmul_removes_monolithic_all_reduce():
    """The latency-hiding decomposition, structurally: the converted
    tp=2 program's all-reduce count EQUALS the tp=1 baseline's (zero
    monolithic model-axis all-reduce survives — and zero re-fuses: the
    count is exact, not an upper bound), the 'matmul' mode adds the
    >= tp-1 chunk-ring collective-permutes, and both modes emit the
    reduce-scatter/all-gather pairs the monolithic op decomposed into."""
    report = probe_collective_matmul()
    c1 = report["collectives_tp1"]
    for mode in ("rsag", "matmul"):
        c = report[f"collectives_tp2_{mode}"]
        assert c["all-reduce"] == c1["all-reduce"], (mode, c, c1)
        assert c["reduce-scatter"] >= 1 and c["all-gather"] >= 1, (mode, c)
    assert report["ring_collective_permutes"] >= 1
    assert report["model_axis_all_reduces_removed"] >= 4


def test_buffers_with_dim_parses_hlo_shapes():
    text = """
  %p0 = f32[8,8,93]{2,1,0} parameter(0)
  %t = (f32[93,16]{1,0}, s32[8,8]{1,0}) tuple(%a, %b)
  %c = bf16[47,16]{1,0} convert(f32[47,16]{1,0} %x)
"""
    assert buffers_with_dim(text, 93) == 2
    assert buffers_with_dim(text, 47) == 2
    assert buffers_with_dim(text, 94) == 0


def test_vocab_parallel_materializes_no_full_vocab_buffer():
    """The vocab-parallel memory claim, structurally: the sharded tp=2
    program's optimized HLO carries ZERO buffers of the (distinctive)
    vocab extent — no [B,L,V] logits, no replicated [V,H] table, no
    vocab-axis all-gather result — while the replicated baseline
    carries them; a silent re-replication of the loss head fails here,
    on CPU, before any hardware window."""
    report = probe_vocab_parallel()
    assert report["baseline_full_vocab_buffers"] > 0
    assert report["vocab_parallel_full_vocab_buffers"] == 0
    # the epilogue's model-axis collectives exist (lookup psum, stat
    # psums/pmax/pmin, backward hidden-cotangent psum)
    extra = (report["collectives_vocab_parallel"]["all-reduce"]
             - report["collectives_baseline"]["all-reduce"])
    assert extra >= 3, report


def test_entry_signature_extracts_step_boundary():
    text = """
HloModule m
%fused (p.0: f32[8,29]) -> f32[8,29] {
  %p.0 = f32[8,29]{1,0} parameter(0)
}
ENTRY %main.1 (Arg_0.1: f32[2,116], Arg_1.2: s32[8]) -> (f32[2,116]) {
  %big = f32[4,8,29]{2,1,0} all-gather(f32[2,116]{1,0} %x)
}
"""
    sig = entry_signature(text)
    # internal computations and step-internal temporaries are excluded
    assert buffers_with_dim(sig, 29) == 0
    assert buffers_with_dim(sig, 116) == 2


def test_decode_probe_helpers_parse_hlo_idioms():
    text = """
  %s = f32[3,2,57,57]{3,2,1,0} parameter(0)
  %dus = f32[2,3,1,57,8]{4,3,2,1,0} dynamic-update-slice(%a, %b, %i0)
  %dus2 = f32[8]{0} dynamic-update-slice-start(%c, %d, %i1)
  %cp = f32[3,1,8,57]{3,2,1,0} copy(f32[3,1,8,57]{2,3,1,0} %t)
  %cp2 = f32[4]{0} copy(f32[4]{0} %u)
"""
    assert buffers_with_dim_repeated(text, 57) == 1   # the [.., 57, 57]
    # times=1 degenerates to a per-shape scan (result + operand shapes)
    assert buffers_with_dim_repeated(text, 57, times=1) == 4
    assert dynamic_update_slices(text) == 2
    assert large_copies_with_dim(text, 57, 3 * 8 * 57) == 1
    assert large_copies_with_dim(text, 57, 10 ** 6) == 0


def test_decode_step_is_buffer_clean_and_in_place():
    """The serving decode claims, tier-1 on CPU: a vocab-parallel decode
    step that re-materializes full-vocab logits, builds a [T, T]
    attention square, regresses the KV write to copy-on-write, or
    unrolls the K-token window into separate dispatches fails CI here
    before any hardware window."""
    report = probe_decode()
    assert report["baseline_full_vocab_buffers"] > 0
    assert report["vocab_parallel_full_vocab_buffers"] == 0
    assert report["dynamic_update_slices_vp"] >= 4    # k+v x 2 layers
    assert report["collectives_vp"]["all-reduce"] >= 4
    assert sum(report["collectives_tp1"].values()) == 0


def test_narrowed_collective_helpers_parse_hlo_idioms():
    text = """
  %ar = f16[8]{0} all-reduce(f16[8]{0} %p), replica_groups={{0,1}}
  %mx = f32[] all-reduce(f32[] %s), to_apply=%max
  %big = f32[64]{0} all-reduce(f32[64]{0} %g)
  %ag = (s8[4]{0}, s8[8]{0}) all-gather-start(s8[4]{0} %x), dimensions={0}
  %rs = bf16[16]{0} reduce-scatter(bf16[32]{0} %y), dimensions={0}
  %c1 = f16[8]{0} convert(f32[8]{0} %a)
  %c2 = f32[8]{0} convert(f16[8]{0} %b)
"""
    n = narrowed_collective_counts(text)
    assert n["all-reduce"] == 1
    assert n["all-gather"] == 1
    assert n["reduce-scatter"] == 1
    # the scalar pmax is an all-reduce but not a payload one
    assert nonscalar_all_reduces(text) == 2
    wire = collective_wire(text)
    assert ("all-reduce", "f16", 8) in wire
    assert ("all-gather", "s8", 8) in wire
    conv = convert_counts(text)
    assert conv["f16"] == 1 and conv["f32"] == 1


def test_quantized_policy_narrows_the_wire():
    """The PR 8 acceptance probe, tier-1 on CPU: the int8-policy tp=2
    program carries the narrowed element type on every policied
    collective operand (convert pairs included), the fp32-policy
    program carries ZERO narrowed collectives, the quantized rs+ag
    pair stays un-re-fused, and the int8 ZeRO-3 gathers narrow per
    (virtual stage, leaf)."""
    report = probe_quantized()
    assert sum(report["narrowed_fp32_policy"].values()) == 0
    assert report["narrowed_tp_psum_int8"]["all-reduce"] >= 4
    assert report["converts_tp_psum_int8"]["f16"] >= 4
    assert report["payload_f32_all_reduces_tp_psum_int8"] >= 1
    assert (report["payload_all_reduces_rsag_int8"]
            == report["payload_all_reduces_tp1"])
    assert report["s8_all_gathers_rsag_int8"] >= 1
    assert (report["narrowed_zero3_int8"]["all-gather"]
            >= report["min_per_layer_gathers"])
    assert report["narrowed_zero3_int8"]["reduce-scatter"] >= 1


def test_zero3_shards_step_boundary_and_gathers_per_layer():
    """The ZeRO-2/3 re-materialization guard, tier-1 on CPU: a stage-3
    program whose returned state regains a full parameter (e.g. a
    reintroduced update all-gather), whose per-layer gathers collapse
    into one bulk materialization (a collective-combiner pass undoing
    the chain), or whose stage-2 grad sync regresses to an all-reduce,
    fails CI here before any hardware window."""
    report = probe_zero3()
    assert report["boundary_full_param_buffers_stage0"] > 0
    assert report["boundary_full_param_buffers_stage3"] == 0
    assert (report["collectives_stage3"]["all-gather"]
            >= report["min_per_layer_gathers"])
    assert report["collectives_stage2"]["reduce-scatter"] >= 1
    assert report["collectives_stage0"]["reduce-scatter"] == 0


def test_probe_cli_json_output(tmp_path, capsys):
    """--json writes the machine-readable report (bench.py embeds it as
    provenance); --probe selects a subset so the CLI contract is
    testable without recompiling every program."""
    out = tmp_path / "probe.json"
    rc = main(["--probe", "single_replica", "--json", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert set(report) == {"single_replica"}
    assert report["single_replica"]["ok"] is True
    assert report["single_replica"]["collectives"]["all-reduce"] == 0
    printed = json.loads(capsys.readouterr().out)
    assert printed == report
