"""CI wiring of the lint CLIs (tools/lint_strategy.py,
tools/lint_source.py) — the `telemetry_report.py --check` pattern:
in-process main() for the rc contract, subprocess for the real CI
spelling, with a budget guard on anything that compiles.
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
TOOLS = os.path.join(REPO, "tools")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# --------------------------------------------------------------------------- #
# tools/lint_source.py — the AST raw-collective lint
# --------------------------------------------------------------------------- #
def test_lint_source_repo_is_clean():
    lint_source = _tool("lint_source")
    assert lint_source.main(["--check"]) == 0


def test_lint_source_flags_raw_collective(tmp_path):
    lint_source = _tool("lint_source")
    pkg = tmp_path / "autodist_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "newlowering.py").write_text(textwrap.dedent("""
        from jax import lax

        def sync(g, axis):
            return lax.psum(g, axis)
    """))
    diags = lint_source.lint_tree(str(tmp_path / "autodist_tpu"))
    assert [d.code for d in diags] == ["ADT201"]
    assert "newlowering.py:5" in diags[0].where


def test_lint_source_catches_aliased_spellings(tmp_path):
    """from-imports and module aliases cannot dodge the guard: every
    local spelling of a forbidden collective is resolved."""
    lint_source = _tool("lint_source")
    pkg = tmp_path / "autodist_tpu"
    pkg.mkdir()
    (pkg / "sneaky.py").write_text(textwrap.dedent("""
        import jax
        import jax.lax as jl
        from jax.lax import all_gather
        from jax.lax import psum as my_sum
        from jax import lax as L

        def a(x, ax):
            return all_gather(x, ax)

        def b(x, ax):
            return my_sum(x, ax)

        def c(x, ax):
            return jl.psum_scatter(x, ax, scatter_dimension=0)

        def d(x, ax):
            return L.psum(x, ax)

        def e(x, ax):
            return jax.lax.psum(x, ax)
    """))
    diags = lint_source.lint_tree(str(pkg))
    assert len(diags) == 5
    assert {d.code for d in diags} == {"ADT201"}


def test_lint_source_honors_pragma_and_allowlist(tmp_path):
    lint_source = _tool("lint_source")
    pkg = tmp_path / "autodist_tpu"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "kernel").mkdir()
    (pkg / "parallel" / "ok.py").write_text(textwrap.dedent("""
        from jax import lax

        def role_sum(g, axis):
            # pipe-axis role reduction:  # lint: allow-raw-collective
            return lax.psum(g, axis)
    """))
    # kernel/ is allowlisted wholesale
    (pkg / "kernel" / "raw.py").write_text(
        "from jax import lax\n\n"
        "def f(g, a):\n    return lax.all_gather(g, a)\n")
    assert lint_source.lint_tree(str(pkg)) == []


def test_lint_source_subprocess_gate():
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint_source.py"),
         "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


# --------------------------------------------------------------------------- #
# tools/lint_strategy.py — plan/program/mutation sweep
# --------------------------------------------------------------------------- #
def test_lint_strategy_files_mode(tmp_path):
    lint_strategy = _tool("lint_strategy")
    from autodist_tpu.analysis.mutations import _pipeline_fixture

    strategy, _, _ = _pipeline_fixture(tensor_parallel=2)
    good = tmp_path / "good.json"
    good.write_text(strategy.to_json())
    assert lint_strategy.main([str(good)]) == 0

    d = json.loads(strategy.to_json())
    d["graph_config"]["lowering"] = "magic"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(d))
    assert lint_strategy.main([str(bad)]) == 1


def test_lint_strategy_zoo_plan_sweep_subprocess(tmp_path):
    """The CI gate: plan-lint the ENTIRE candidate zoo AND the
    topology-aware searched frontier in a fresh process.  Budget
    guard: --plan-only --no-decode skips every compile (the program
    level is covered in-process by test_analysis.py over the shared
    memoized corpus, and the searched winner's program lint by
    test_search.py)."""
    out = tmp_path / "zoo.json"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO})
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint_strategy.py"),
         "--zoo", "--search", "--check", "--plan-only", "--no-decode",
         "--json", str(out)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    report = json.loads(out.read_text())
    # the sweep actually covered the zoo: both fixture families, and
    # the marquee candidates among them
    names = [r["candidate"] for r in report["zoo"]]
    assert any(n.startswith("generic/") for n in names)
    assert sum(n.startswith("pipeline_lm/") for n in names) >= 5
    for rec in report["zoo"]:
        errors = [d for d in rec["plan"]
                  if d["severity"] == "error"]
        assert not errors, (rec["candidate"], errors)
    # ... and the searched frontier: every fixture topology (incl. the
    # two-slice one) enumerated a real cross-product, synthesized
    # nothing unlintable, and elected a winner.
    fixtures = {r["fixture"]: r for r in report["search"]}
    assert "pipeline_lm@2slice" in fixtures
    for rec in fixtures.values():
        assert rec["counts"]["priced"] > 0, rec
        assert rec["lint_pruned"] == [], rec
        assert rec["survivor_errors"] == 0, rec
        assert rec["winner"], rec
    assert fixtures["pipeline_lm@2slice"]["counts"]["raw_configs"] >= 300
    # the two-slice frontier's cross-slice term is priced (at DCN
    # constants): some candidate carries a nonzero dcn time
    assert any(c["dcn_time_s"] > 0
               for c in fixtures["pipeline_lm@2slice"]["frontier"])


def test_lint_strategy_max_programs_budget_is_loud():
    """--max-programs N drops compiles but never silently: every
    skipped program is listed in the report (no-silent-caps)."""
    lint_strategy = _tool("lint_strategy")
    n_err, _, results = lint_strategy.lint_zoo(
        max_programs=0, decode=True, out=lambda *a, **k: None)
    assert n_err == 0
    skipped = [r for r in results
               if r.get("program") == "skipped (--max-programs budget)"]
    assert skipped, "budget guard left no audit trail"


def test_lint_strategy_mutate_mode_in_process():
    """`--mutate` (plan half): the harness reports one record per
    mutation and rc 0 exactly when every rule fires.  The compile-heavy
    program half runs in test_analysis.py over the shared corpus."""
    from autodist_tpu.analysis.mutations import run_mutations

    results = run_mutations(kinds=["plan"])
    assert all(r["ok"] for r in results), [
        r for r in results if not r["ok"]]
    # the CLI's rc contract over the same records
    lint_strategy = _tool("lint_strategy")
    failed, _ = lint_strategy.run_mutation_matrix(
        out=lambda *a, **k: None)
    assert failed == 0
