"""Model-zoo integration tests: each model trains under representative
strategies on the simulated mesh (≙ the reference's case-file × strategy
cross-product, ``tests/integration/test_all.py:35-70``), with loss-decrease
assertions rather than liveness only."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, Parallax, PartitionedPS
from autodist_tpu import models


pytestmark = pytest.mark.slow

def run_steps(trainable, batches, builder, **ad_kw):
    runner = AutoDist({}, builder, **ad_kw).build(trainable)
    losses = [float(runner.step(b)["loss"]) for b in batches]
    return runner, losses


def test_linear_regression_converges():
    # ≙ reference examples/linear_regression.py: must actually fit
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)
    batches = []
    for _ in range(30):
        x = rng.randn(32, 13).astype(np.float32)
        batches.append({"x": x, "y": x @ w_true})
    t = models.make_linear_regression_trainable(optax.sgd(0.1))
    _, losses = run_steps(t, batches, AllReduce())
    assert losses[-1] < 0.05 * losses[0]


@pytest.mark.parametrize("builder", [AllReduce(chunk_size=4), PartitionedPS()],
                         ids=["allreduce", "fsdp"])
def test_mnist_cnn_trains(builder):
    rng = np.random.RandomState(1)
    t = models.make_cnn_trainable(optax.adam(1e-3), jax.random.PRNGKey(0))
    batches = [{"x": rng.randn(16, 28, 28, 1).astype(np.float32),
                "y": rng.randint(0, 10, (16,)).astype(np.int32)}
               for _ in range(5)]
    _, losses = run_steps(t, batches, builder)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_resnet18_with_synced_bn():
    rng = np.random.RandomState(2)
    model = models.ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
    t = models.make_resnet_trainable(model, optax.sgd(0.01, momentum=0.9),
                                     jax.random.PRNGKey(0), image_size=32,
                                     batch_size=8)
    batches = [{"x": rng.randn(16, 32, 32, 3).astype(np.float32),
                "y": rng.randint(0, 10, (16,)).astype(np.int32)}
               for _ in range(3)]
    runner, losses = run_steps(t, batches, AllReduce())
    assert np.isfinite(losses).all()
    # batch_stats must update and stay replicated/invariant
    bs = runner.get_extra()["batch_stats"]
    mean0 = jax.tree_util.tree_leaves(bs)[0]
    assert np.isfinite(np.asarray(mean0)).all()


def test_transformer_lm_trains():
    cfg = models.TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        mlp_dim=128, max_len=32, dtype=jnp.float32, dropout_rate=0.0)
    model = models.TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((4, 16), jnp.int32)
    params = model.init({"params": rng}, tokens)["params"]

    from autodist_tpu.capture import Trainable

    def loss(p, extra, batch, step_rng):
        logits = model.apply({"params": p}, batch["x"],
                             deterministic=True)
        l, metrics = models.lm_loss_head(logits, batch)
        return l, extra, dict(metrics, loss=l)

    t = Trainable(loss, params, optax.adam(1e-3), name="lm")
    r = np.random.RandomState(3)
    batches = [{"x": r.randint(0, 256, (8, 16)).astype(np.int32),
                "y": r.randint(0, 256, (8, 16)).astype(np.int32)}
               for _ in range(4)]
    _, losses = run_steps(t, batches, AllReduce())
    assert losses[-1] < losses[0]


def test_bert_mlm_trains_parallax():
    cfg = models.TransformerConfig(
        vocab_size=1000, hidden_size=32, num_layers=1, num_heads=2,
        mlp_dim=64, max_len=32, dtype=jnp.float32, dropout_rate=0.0)
    t = models.make_mlm_trainable(cfg, optax.adam(1e-3),
                                  jax.random.PRNGKey(0), batch_size=8,
                                  seq_len=16, num_masked=4)
    # token_embed must route to PS/sharded under Parallax
    strat = Parallax().build(t, __import__("autodist_tpu").ResourceSpec({}))
    by_name = {n.var_name: n for n in strat.node_configs}
    assert by_name["token_embed/embedding"].synchronizer.kind == "ps"

    batches = [models.synthetic_mlm_batch(s, 8, 16, 4, 1000)
               for s in range(3)]
    _, losses = run_steps(t, batches, Parallax())
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_lm1b_sampled_softmax_trains():
    t = models.make_lm1b_trainable(optax.adagrad(0.1), jax.random.PRNGKey(0),
                                   vocab_size=2000, embed_dim=32,
                                   hidden_dim=32, seq_len=8, batch_size=8,
                                   num_samples=16)
    r = np.random.RandomState(4)
    batches = [{"x": r.randint(0, 2000, (8, 8)).astype(np.int32),
                "y": r.randint(0, 2000, (8, 8)).astype(np.int32)}
               for _ in range(3)]
    _, losses = run_steps(t, batches, Parallax())
    assert np.isfinite(losses).all()


def test_ncf_trains():
    t = models.make_ncf_trainable(optax.adam(1e-3), jax.random.PRNGKey(0))
    r = np.random.RandomState(5)
    batches = [{"users": r.randint(0, 1000, (32,)).astype(np.int32),
                "items": r.randint(0, 500, (32,)).astype(np.int32),
                "labels": r.randint(0, 2, (32,)).astype(np.int32)}
               for _ in range(4)]
    _, losses = run_steps(t, batches, AllReduce())
    assert losses[-1] < losses[0]


def test_sampled_softmax_rewards_true_label():
    """Property check: the sampled-softmax loss must be much lower when the
    hidden states align with the true labels' output embeddings than for
    random hidden states (the objective points the same way as full CE)."""
    rng = jax.random.PRNGKey(0)
    V, H, B = 500, 16, 64
    w = jax.random.normal(rng, (V, H))
    b = jnp.zeros((V,))
    labels = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, V)
    h_aligned = 4.0 * w[labels]
    h_random = jax.random.normal(jax.random.PRNGKey(1), (B, H))
    l_aligned = models.sampled_softmax_loss(
        jax.random.PRNGKey(3), w, b, h_aligned, labels, 128, V)
    l_random = models.sampled_softmax_loss(
        jax.random.PRNGKey(3), w, b, h_random, labels, 128, V)
    assert float(l_aligned) < float(l_random) - 1.0
    # accidental-hit masking: true label among negatives must not blow up
    assert np.isfinite(float(l_aligned))


@pytest.mark.parametrize("factory,size", [
    (lambda: models.VGG16(num_classes=10, hidden=64, dtype=jnp.float32), 32),
    (lambda: models.DenseNet121(num_classes=10, growth_rate=8,
                                dtype=jnp.float32), 32),
], ids=["vgg16", "densenet121"])
def test_imagenet_zoo_trains(factory, size):
    # ≙ reference examples/benchmark/imagenet.py model flag (VGG16,
    # DenseNet121); tiny widths/images for CPU test speed.
    rng = np.random.RandomState(6)
    t = models.make_image_trainable(factory(), optax.sgd(0.01),
                                    jax.random.PRNGKey(0), image_size=size,
                                    batch_size=8)
    batches = [{"x": rng.randn(8, size, size, 3).astype(np.float32),
                "y": rng.randint(0, 10, (8,)).astype(np.int32)}
               for _ in range(2)]
    _, losses = run_steps(t, batches, AllReduce())
    assert np.isfinite(losses).all()


def test_inception_v3_forward_shape():
    # Full InceptionV3 topology check (299x299 stem → 8x8 grid → logits);
    # forward-only at batch 1 to keep CPU time bounded.
    model = models.InceptionV3(num_classes=7, dtype=jnp.float32)
    x = jnp.zeros((1, 299, 299, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 7)


def test_vgg_dropout_and_eval_mode():
    """Dropout needs an rng at train time; eval must be inference-mode
    (deterministic, dropout off)."""
    rng = np.random.RandomState(7)
    model = models.VGG11(num_classes=10, hidden=32, dropout_rate=0.5,
                         dtype=jnp.float32)
    t = models.make_image_trainable(model, optax.sgd(0.01),
                                    jax.random.PRNGKey(0), image_size=32,
                                    batch_size=8)
    batch = {"x": rng.randn(8, 32, 32, 3).astype(np.float32),
             "y": rng.randint(0, 10, (8,)).astype(np.int32)}
    runner, losses = run_steps(t, [batch], AllReduce())
    assert np.isfinite(losses).all()
    e1 = runner.eval_step(batch, rng=jax.random.PRNGKey(1))
    e2 = runner.eval_step(batch, rng=jax.random.PRNGKey(2))
    assert float(e1["loss"]) == float(e2["loss"])
