"""MoE expert parallelism: the all_to_all dispatch must compute exactly
what the single-device dense reference computes per token group, and the
layer must train."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from autodist_tpu.parallel.moe import (dense_moe_reference,
                                       expert_parallel_ffn, top2_gating)

pytestmark = pytest.mark.slow

Pdev, G, E, M, H = 4, 8, 8, 16, 32
E_local = E // Pdev


def make_weights(seed=0):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(M, E), jnp.float32) * 0.5,
            jnp.asarray(r.randn(E, M, H), jnp.float32) * 0.2,
            jnp.asarray(r.randn(E, H, M), jnp.float32) * 0.2)


def test_expert_parallel_matches_dense():
    gate_w, wi, wo = make_weights()
    r = np.random.RandomState(1)
    tokens = jnp.asarray(r.randn(Pdev * G, M), jnp.float32)
    mesh = jax.make_mesh((Pdev,), ("expert",))

    def run(tokens, gate_w, wi, wo):
        out, aux = expert_parallel_ffn(tokens, gate_w, wi, wo,
                                       capacity_factor=8.0)
        return out, lax.pmean(aux, "expert")

    fn = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(P("expert"), P(), P("expert"), P("expert")),
        out_specs=(P("expert"), P()), check_vma=False))
    out, aux = fn(tokens, gate_w, wi, wo)
    out = np.asarray(out)

    # capacity in the distributed layer: ceil(2*G*cf/E) with cf=8 -> 16
    capacity = max(int(np.ceil(2 * G * 8.0 / E)), 4)
    for p in range(Pdev):
        shard = tokens[p * G:(p + 1) * G]
        ref, _ = dense_moe_reference(shard, gate_w, wi, wo, capacity)
        np.testing.assert_allclose(out[p * G:(p + 1) * G], np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    assert np.isfinite(float(aux))


def test_top2_gating_capacity_drops():
    """With capacity 1 per expert, overflow tokens must be dropped, not
    mis-routed."""
    logits = jnp.asarray(np.tile([[5.0, 1.0, 0.0, 0.0]], (6, 1)), jnp.float32)
    dispatch, combine, aux = top2_gating(logits, capacity=1)
    # expert 0 can take exactly one token in slot 0
    assert float(dispatch[:, 0].sum()) == 1.0
    # weights normalized and bounded
    assert float(combine.max()) <= 1.0 + 1e-6


def test_moe_trains():
    gate_w, wi, wo = make_weights(2)
    mesh = jax.make_mesh((Pdev,), ("expert",))
    r = np.random.RandomState(3)
    x = r.randn(Pdev * G, M).astype(np.float32)
    y = (x @ r.randn(M, M).astype(np.float32) * 0.1)

    params = {"gate": gate_w, "wi": wi, "wo": wo}
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    def local_loss(params, xb, yb):
        out, aux = expert_parallel_ffn(xb, params["gate"], params["wi"],
                                       params["wo"], capacity_factor=4.0)
        return jnp.mean((out - yb) ** 2) + 0.01 * aux

    def step(params, opt_state, xb, yb):
        def total(p):
            l = local_loss(p, xb, yb)
            return l
        l, g = jax.value_and_grad(total)(params)
        # experts sharded: their grads are local; gate replicated: pmean
        g = {"gate": lax.pmean(g["gate"], "expert"),
             "wi": g["wi"], "wo": g["wo"]}
        l = lax.pmean(l, "expert")
        u, new_opt = opt.update(g, opt_state, params)
        return optax.apply_updates(params, u), new_opt, l

    specs_p = {"gate": P(), "wi": P("expert"), "wo": P("expert")}

    # adam state mirrors the params tree: expert leaves sharded, rest rep.
    def opt_spec(leaf):
        if getattr(leaf, "ndim", 0) == 3:
            return P("expert")
        if getattr(leaf, "ndim", 0) == 2:
            return P()
        return P()
    o_spec_tree = jax.tree.map(opt_spec, opt_state)

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs_p, o_spec_tree, P("expert"), P("expert")),
        out_specs=(specs_p, o_spec_tree, P()), check_vma=False))

    losses = []
    for _ in range(10):
        params, opt_state, l = fn(params, opt_state, x, y)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# --------------------------------------------------------------------------- #
# End-to-end trajectory goldens: the expert-parallel LM vs the dense
# single-device reference, across the strategy grid (PR 18).
# --------------------------------------------------------------------------- #
STEPS = 4


def _moe_cfg():
    from autodist_tpu.models.moe_transformer import MoeConfig

    # capacity_factor 4.0 gives every top-2 route a slot at this token
    # count, so sharded-vs-dense routing parity is exact and the only
    # trajectory deviations are collective arithmetic (wire precision,
    # per-shard aux-loss averaging) — measured max |dnll| <= 4.5e-4
    # across the whole grid, 10x inside the tolerance below.
    return MoeConfig(vocab_size=64, hidden_size=16, num_layers=1,
                     num_heads=2, expert_hidden=32, num_experts=4,
                     capacity_factor=4.0, max_len=8, dtype=jnp.float32)


def _moe_trajectory(runner):
    r = np.random.RandomState(0)
    nlls = []
    try:
        for _ in range(STEPS):
            x = r.randint(0, 64, (8, 8)).astype(np.int32)
            m = runner.step({"x": x, "y": np.roll(x, -1, axis=1)})
            nlls.append(float(np.asarray(m["nll"])))
    finally:
        runner.close()
    return nlls


def _moe_trainable(expert_sharded):
    from autodist_tpu.models.moe_transformer import make_moe_lm_trainable

    return make_moe_lm_trainable(_moe_cfg(), optax.adam(1e-2),
                                 jax.random.PRNGKey(0), batch_size=8,
                                 seq_len=8, expert_sharded=expert_sharded)


@functools.lru_cache(maxsize=1)
def _dense_reference_nlls():
    from autodist_tpu import AutoDist

    runner = AutoDist({"topology": {"platform": "cpu",
                                    "num_devices": 1}},
                      "AllReduce").build(_moe_trainable(False))
    return tuple(_moe_trajectory(runner))


@pytest.mark.parametrize("expert,zero_stage,precision", [
    (2, 1, None), (2, 3, None), (4, 1, None), (4, 3, None),
    (2, 1, "int8"), (2, 3, "int8"), (4, 1, "int8"), (4, 3, "int8"),
])
def test_moe_lm_trajectory_matches_dense(expert, zero_stage, precision):
    """The sharded LM's nll trajectory tracks the dense single-device
    reference across expert-degree x ZeRO x wire-precision — the
    all_to_all round trip, the local-expert grads, and the quantized
    wire must all preserve training semantics."""
    from autodist_tpu import AutoDist

    mesh = {"expert": expert} if expert == 4 \
        else {"data": 4 // expert, "expert": expert}
    runner = AutoDist(
        {"topology": {"platform": "cpu", "num_devices": 4},
         "mesh": mesh},
        "ExpertParallel", zero_stage=zero_stage, num_experts=4,
        capacity_factor=4.0,
        collective_precision=({"moe_a2a": precision} if precision
                              else None)).build(_moe_trainable(True))
    nlls = _moe_trajectory(runner)
    ref = _dense_reference_nlls()
    assert np.isfinite(nlls).all()
    np.testing.assert_allclose(nlls, ref, atol=5e-3)


def test_moe_lm_trajectory_with_a2a_ring_kernel():
    """The fused-ring wire (per-chunk scales, s8 ppermute hops) stays
    inside the same trajectory envelope as the composed int8 sandwich."""
    from autodist_tpu import AutoDist

    runner = AutoDist(
        {"topology": {"platform": "cpu", "num_devices": 4},
         "mesh": {"expert": 4}},
        "ExpertParallel", zero_stage=1, num_experts=4,
        capacity_factor=4.0, collective_precision={"moe_a2a": "int8"},
        kernel=("a2a_ring",)).build(_moe_trainable(True))
    nlls = _moe_trajectory(runner)
    np.testing.assert_allclose(nlls, _dense_reference_nlls(), atol=5e-3)
