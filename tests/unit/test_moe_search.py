"""Expert parallelism as a search citizen (PR 18): election pins.

The two-slice search over the MoE trainable must (a) elect the expert
lowering over its own dense point on the merits of the priced a2a
term, (b) keep the expert axis within a slice under default link
constants and deliberately cross DCN only when inverted constants make
the a2a cheaper there (ADT061 stays a WARNING so the candidate is
electable), and (c) elect the fused a2a_ring kernel exactly when the
calibratable kernel constants favor it — both directions pinned, so a
constant regression in either the pricing or the candidate family
breaks a test, not silently the election.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                 make_moe_lm_trainable)
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.simulator.search import search_strategies

pytestmark = pytest.mark.slow

VOCAB = 32


def make_moe_lm():
    cfg = MoeConfig(vocab_size=VOCAB, hidden_size=16, num_layers=1,
                    num_heads=4, expert_hidden=32, num_experts=8,
                    max_len=8, dtype=jnp.float32)
    return make_moe_lm_trainable(cfg, optax.adam(1e-3),
                                 jax.random.PRNGKey(0), batch_size=4,
                                 seq_len=8)


def two_slice_spec():
    return ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8,
                                      "num_slices": 2}})


def _search(**cost_model_kwargs):
    return search_strategies(make_moe_lm(), two_slice_spec(),
                             global_batch=8, **cost_model_kwargs)


def test_moe_search_elects_expert_within_slice_with_ring():
    """Default constants: the MoE point beats its own dense sibling on
    the priced a2a term, the expert axis stays within a slice, the wire
    is int8, and the fused ring is elected (launches at the fused alpha
    + halved q/dq beat the composed sandwich)."""
    res = _search()
    win = res.winner
    assert win is not None and win.config is not None
    assert win.config.expert > 1            # MoE beat the dense point
    assert not win.config.expert_over_dcn   # a2a stays on ICI
    assert win.config.collective_precision == "int8"
    assert win.config.kernel == "fused"     # a2a_ring elected
    assert "a2a_ring" in (win.strategy.graph_config.kernel or {})
    # the election was real: the frontier priced dense siblings too,
    # and the a2a term is broken out on the winner's cost.
    assert any(c.config is not None and c.config.expert == 1
               for c in res.frontier)
    assert win.cost.a2a_bytes > 0
    assert win.cost.a2a_time_s > 0


def test_moe_search_inverted_links_elect_expert_over_dcn():
    """Pathological links (starved ICI, abundant low-alpha DCN) flip
    the placement: the expert axis deliberately spans slices — the
    candidate must survive its ADT061 WARNING to be electable."""
    res = _search(link_profile={"ici_gbps": 0.05, "dcn_gbps": 500.0,
                                "dcn_alpha_s": 1e-7})
    win = res.winner
    assert win is not None and win.config is not None
    assert win.config.expert > 1
    assert win.config.expert_over_dcn


def test_moe_search_unfavorable_kernel_constants_keep_composed():
    """Calibrated constants that price the fused hops slow and the
    in-hop q/dq expensive un-elect the ring: the winner keeps the int8
    wire but through the composed quantize->all_to_all->dequantize."""
    res = _search(kernel_profile={"fused_hop_alpha_s": 1e-4,
                                  "a2a_ring_qdq_factor": 4.0})
    win = res.winner
    assert win is not None and win.config is not None
    assert win.config.expert > 1
    assert not win.config.expert_over_dcn
    assert win.config.collective_precision == "int8"
    assert win.config.kernel is None
    assert "a2a_ring" not in (win.strategy.graph_config.kernel or {})


def test_moe_search_winner_lowers_and_trains():
    """The elected strategy is not just priceable — it builds on its
    own re-factored spec and takes a finite training step."""
    res = _search()
    win = res.winner
    runner = AutoDist(win.spec, "AllReduce").build(make_moe_lm(),
                                                   win.strategy)
    try:
        r = np.random.RandomState(0)
        x = r.randint(0, VOCAB, (8, 8)).astype(np.int32)
        m = runner.step({"x": x, "y": np.roll(x, -1, axis=1)})
        assert np.isfinite(float(np.asarray(m["loss"])))
    finally:
        runner.close()
