"""Tier-3 distributed test: the product's own launcher as test harness.

The reference's distributed tier ran the *product's own*
``Cluster``/``Coordinator`` to SSH into a worker container and asserted
exact post-update values cross-node (``tests/integration/test_dist.py:
25-43``, ``Jenkinsfile`` chief/worker stages).  Here: a chief process
(spawned by pytest) uses ``Cluster.launch_clients`` to start a worker
process running the same script; both ``resource.bootstrap()`` into one
``jax.distributed`` job over gloo CPU collectives (2 processes x 2
virtual devices), hand the strategy off through the authenticated
coordination service, feed through ``make_global_batch``'s multi-process
branch, train, and the result must equal the single-process run.
"""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCRIPT = """
import os, sys

# Per-process: 2 virtual CPU devices; gloo for cross-process collectives.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import optax

from autodist_tpu import AutoDist, AllReduce, Trainable
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.runtime.cluster import Cluster, make_global_batch

IS_CHIEF = not os.environ.get("AUTODIST_TPU_WORKER")
COORD_PORT = int(os.environ["TEST_COORD_PORT"])
OUT = os.environ["TEST_OUT"]
STEPS = 3

def make_trainable():
    # numpy params: nothing may touch the jax backend before bootstrap.
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(6, 3).astype(np.float32),
              "b": np.zeros(3, np.float32)}
    def loss_fn(p, batch):
        import jax.numpy as jnp
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)
    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))

def global_batch(step):
    rng = np.random.RandomState(100 + step)
    return {"x": rng.randn(16, 6).astype(np.float32),
            "y": rng.randn(16, 3).astype(np.float32)}

trainable = make_trainable()

if IS_CHIEF:
    os.environ["AUTODIST_TPU_NUM_PROCESSES"] = "2"
    os.environ["AUTODIST_TPU_PROCESS_ID"] = "0"
    os.environ["AUTODIST_TPU_COORDINATOR"] = f"127.0.0.1:{COORD_PORT}"
    rs = ResourceSpec({"topology": {"num_devices": 4}})
    # Plan from the declared inventory (backend not initialized yet).
    strategy = AllReduce(chunk_size=2).build(trainable, rs)
    cluster = Cluster(rs, hosts=["localhost"])
    cluster.launch_clients(strategy, argv=[sys.executable,
                                           os.path.abspath(__file__)])
else:
    rs = ResourceSpec({"topology": {"num_devices": 4}})
    strategy = None

ad = AutoDist(rs, AllReduce(chunk_size=2))      # bootstrap: rendezvous
runner = ad.build(trainable, strategy=strategy)  # workers load by ID

pid = rs.process_id
for step in range(STEPS):
    g = global_batch(step)
    half = 16 // 2
    local = {k: v[pid * half:(pid + 1) * half] for k, v in g.items()}
    batch = make_global_batch(local, runner.mesh)
    metrics = runner.step(batch)

# Two more steps as ONE fused dispatch (steps-per-loop across processes):
# global stacked batches carry the steps axis ahead of the feed spec.
from jax.sharding import PartitionSpec as P
gs = [global_batch(3), global_batch(4)]
half = 16 // 2
local_stack = {k: np.stack([g[k][pid * half:(pid + 1) * half] for g in gs])
               for k in gs[0]}
stacked = make_global_batch(local_stack, runner.mesh, P(None, "data"))
runner.run_steps(stacked)

if IS_CHIEF:
    params = runner.get_params()
    np.savez(OUT, **params)
# Leave the jax.distributed job symmetrically BEFORE the chief joins
# worker processes: shutdown is a collective barrier, so a chief that
# joins first deadlocks against a worker blocked in its exit barrier.
jax.distributed.shutdown()
if IS_CHIEF:
    cluster.join(timeout=60)
"""


@pytest.mark.parametrize("dummy", [0], ids=["2proc"])
def test_two_process_training_matches_single_process(tmp_path, dummy):
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    script = tmp_path / "train2.py"
    script.write_text(SCRIPT)
    out = tmp_path / "params.npz"
    env = dict(os.environ,
               PYTHONPATH=REPO_ROOT,
               TEST_COORD_PORT=str(port),
               TEST_OUT=str(out))
    # Scratch working dir: the strategy hand-off must ride the
    # coordination service, not a shared filesystem.
    env["AUTODIST_TPU_WORKING_DIR"] = str(tmp_path / "scratch")
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_NUM_PROCESSES",
              "AUTODIST_TPU_PROCESS_ID", "XLA_FLAGS", "JAX_PLATFORMS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=str(tmp_path),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"chief failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    got = dict(np.load(out))

    # Single-process reference: same global batches, plain optax.
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 3), jnp.float32),
              "b": jnp.zeros(3, jnp.float32)}
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    for step in range(5):   # 3 per-step + 2 fused in the script
        r = np.random.RandomState(100 + step)
        batch = {"x": jnp.asarray(r.randn(16, 6), jnp.float32),
                 "y": jnp.asarray(r.randn(16, 3), jnp.float32)}
        grads = jax.grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(got["w"], np.asarray(params["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got["b"], np.asarray(params["b"]),
                               rtol=1e-5, atol=1e-6)
