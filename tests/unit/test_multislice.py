"""Multi-slice (DCN) mesh tests: hierarchical data parallelism.

The reference scaled over a flat NCCL ring; multi-slice TPU pods add an
outer replica axis over DCN (SURVEY.md §5.8 "multi-slice → DCN
collectives").  Every strategy must produce identical numerics over a
``dcn × data`` mesh — the collectives just span both axes and XLA lowers
them hierarchically.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, Parallax, PartitionedPS, PS,
                          Trainable)
from autodist_tpu.resource import ResourceSpec

from tests.unit.test_end_to_end import (make_batch, make_trainable,
                                        single_device_reference)

SPEC = {"topology": {"num_devices": 8}, "mesh": {"dcn": 2, "data": 4}}


pytestmark = pytest.mark.slow

@pytest.mark.parametrize("builder", [AllReduce, PS, PartitionedPS],
                         ids=["AllReduce", "PS-ZeRO1", "PartitionedPS"])
def test_multislice_matches_single_device(builder):
    batches = [make_batch(s) for s in range(3)]
    expected = single_device_reference(make_trainable(), batches)
    runner = AutoDist(SPEC, builder()).build(make_trainable())
    assert runner.lowered.plan.repl_axes == ("dcn", "data")
    assert runner.lowered.plan.num_replicas == 8
    for b in batches:
        runner.step(b)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-6, atol=2e-6),
        runner.get_params(), jax.device_get(expected))


def test_multislice_sparse_embedding():
    """Vocab-sharded embedding over dcn x data: touched-rows path spans
    both axes."""
    from tests.unit.test_sparse import (make_batch as sp_batch,
                                        make_trainable as sp_trainable,
                                        single_device_reference as sp_ref)

    trainable = sp_trainable(optax.adam(1e-2))
    runner = AutoDist(SPEC, Parallax()).build(trainable)
    assert runner.lowered.plan.var_plans["embedding"].sparse_lookup
    batches = [sp_batch(s) for s in range(2)]
    for b in batches:
        runner.step(b)
    got = runner.get_params()
    want = sp_ref(sp_trainable(optax.adam(1e-2)), batches)
    np.testing.assert_allclose(np.asarray(got["embedding"]),
                               np.asarray(want["embedding"]),
                               rtol=2e-6, atol=2e-6)


def test_num_slices_topology_shorthand():
    rs = ResourceSpec({"topology": {"num_devices": 8, "num_slices": 2}})
    assert rs.resolved_mesh_shape() == {"dcn": 2, "data": 4}
    runner = AutoDist(rs, AllReduce()).build(make_trainable())
    m = runner.step(make_batch(0))
    assert np.isfinite(float(np.asarray(m["loss"])))
    with pytest.raises(ValueError, match="slices"):
        ResourceSpec({"topology": {"num_devices": 8, "num_slices": 3}}
                     ).resolved_mesh_shape()


def test_sequence_parallel_syncs_across_dcn():
    """Multi-slice + sequence parallelism: gradients must cross the dcn
    axis too (a data-only pmean would silently skip cross-slice sync).
    Golden vs single device over a dcn x data x seq mesh."""
    import optax
    from jax.sharding import Mesh

    from autodist_tpu.parallel.sequence import lower_sequence_parallel

    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("dcn", "data", "seq"))

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(16, 4),
                               jnp.float32)}

    def loss_fn(p, batch):
        # token-mean loss; no attention needed for the sync-axes check
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    t = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.2))
    init_fn, step_fn, _ = lower_sequence_parallel(t, mesh)
    state = init_fn(t.params, None)
    r = np.random.RandomState(1)
    b = {"x": r.randn(8, 8, 16).astype(np.float32),
         "y": r.randn(8, 8, 4).astype(np.float32)}
    for _ in range(2):
        state, _ = step_fn(state, jax.tree.map(jnp.asarray, b),
                           jax.random.PRNGKey(0))

    ref_p = params
    opt_state = t.optimizer.init(ref_p)
    for _ in range(2):
        g = jax.grad(lambda p: loss_fn(p, jax.tree.map(jnp.asarray, b)))(ref_p)
        upd, opt_state = t.optimizer.update(g, opt_state, ref_p)
        ref_p = __import__("optax").apply_updates(ref_p, upd)

    np.testing.assert_allclose(
        np.asarray(jax.device_get(state["params"]["w"])),
        np.asarray(jax.device_get(ref_p["w"])), rtol=1e-5, atol=1e-5)
