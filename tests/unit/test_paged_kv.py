"""Paged-KV serving goldens: block-granular allocation, block-aware
admission, the paged flash-decode kernel, and the sampling rung.

The acceptance bar (ISSUE 14): greedy decode under ``kv_layout="paged"``
matches the dense engine token-for-token across tp∈{1,2} ×
vocab-parallel — including the eviction/re-admission edge where a freed
block is reused by a new request mid-stream — the paged flash kernel
matches the composed gather+attention golden across block-boundary edge
lengths, a short-request mix admits strictly MORE concurrent requests
under paged than dense at equal pool bytes, and the ``decode_cost``
capacity objective elects paged exactly when length variance makes
dense reservation wasteful (both directions).  Plus the allocator's
coded-exhaustion/accounting contract and the sampling rung's
interleave-parity extension.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.serving import (BlockAllocator, ContinuousBatcher,
                                  PoolExhaustedError, ServingEngine)
from autodist_tpu.serving import kv_cache
from autodist_tpu.serving.engine import seed_engine_kwargs

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V = 33          # odd: V % 2 != 0 exercises the vocab zero-pad path
MAX_LEN = 24
PROMPT = [3, 1, 4, 1, 5]


def make_cfg(vocab=V, max_len=MAX_LEN):
    return TransformerConfig(
        vocab_size=vocab, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_len=max_len, dtype=jnp.float32,
        dropout_rate=0.0, attention_dropout_rate=0.0)


@pytest.fixture(scope="module")
def cfg():
    return make_cfg()


@pytest.fixture(scope="module")
def params(cfg):
    return make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params


def make_engine(cfg, params, *, kv_layout="dense", tp=1,
                vocab_parallel=False, slots=2, decode_steps=3,
                prefill_len=8, **kw):
    return ServingEngine(cfg, params, tensor_parallel=tp,
                         vocab_parallel=vocab_parallel, num_slots=slots,
                         max_len=cfg.max_len, prefill_len=prefill_len,
                         decode_steps=decode_steps, kv_layout=kv_layout,
                         **kw)


# --------------------------------------------------------------------- #
# the block allocator (pure host accounting)
# --------------------------------------------------------------------- #
def test_allocator_exhaustion_is_coded():
    a = BlockAllocator(4)
    got = a.alloc(3)
    assert len(got) == 3 and a.free_blocks == 1
    with pytest.raises(PoolExhaustedError, match="kv_pool_exhausted"):
        a.alloc(2)
    # the failed alloc must not leak blocks
    assert a.free_blocks == 1 and a.used_blocks == 3


def test_allocator_fragmentation_free_accounting():
    """One flat free list: any n <= free allocation succeeds whatever
    the alloc/free interleaving, and free + used == total always."""
    a = BlockAllocator(8)
    r = np.random.RandomState(0)
    held = []
    for _ in range(200):
        assert a.free_blocks + a.used_blocks == 8
        if held and r.rand() < 0.5:
            blocks = held.pop(r.randint(len(held)))
            a.free(blocks)
        else:
            n = int(r.randint(0, a.free_blocks + 1))
            held.append(a.alloc(n))
    # by construction no allocation of n <= free can ever fail
    a.free([b for blocks in held for b in blocks])
    assert a.free_blocks == 8 and a.used_blocks == 0
    assert sorted(a.alloc(8)) == list(range(8))


def test_allocator_rejects_double_free_and_foreign_ids():
    a = BlockAllocator(3)
    blocks = a.alloc(2)
    a.free(blocks)
    with pytest.raises(ValueError, match="double-free or"):
        a.free(blocks)
    b = BlockAllocator(3)
    b.alloc(1)
    with pytest.raises(ValueError, match="not allocated"):
        b.free([99])


def test_blocks_for_math():
    assert kv_cache.blocks_for(0, 16) == 0
    assert kv_cache.blocks_for(1, 16) == 1
    assert kv_cache.blocks_for(16, 16) == 1
    assert kv_cache.blocks_for(17, 16) == 2
    assert kv_cache.blocks_for(-3, 16) == 0


def test_init_paged_cache_validates_pool():
    with pytest.raises(ValueError, match="cannot hold even one"):
        kv_cache.init_paged_cache(1, 2, 2, 4, max_len=64, block_len=16,
                                  num_blocks=3)
    c = kv_cache.init_paged_cache(2, 3, 2, 4, max_len=32, block_len=8,
                                  num_blocks=10)
    assert c.k.shape == (2, 10, 2, 8, 4)
    assert c.block_table.shape == (3, 4)
    # pytree: the whole cache rides jit carries in one piece
    leaves = jax.tree_util.tree_leaves(c)
    assert len(leaves) == 4


# --------------------------------------------------------------------- #
# paged attention vs the dense math, and the paged flash kernel
# --------------------------------------------------------------------- #
def test_paged_cached_attention_matches_dense_with_identity_table():
    """With the table laying blocks out contiguously, the gathered lane
    IS the dense lane — paged attention must equal dense attention
    bit-for-bit."""
    rng = np.random.RandomState(0)
    B, H, d, bl, mb = 2, 2, 8, 8, 3
    T = mb * bl
    k_lane = jnp.asarray(rng.randn(B, H, T, d), jnp.float32)
    v_lane = jnp.asarray(rng.randn(B, H, T, d), jnp.float32)
    q = jnp.asarray(rng.randn(B, 1, H, d), jnp.float32)
    lengths = jnp.asarray([5, 17], jnp.int32)
    # pool block  s*mb + j  holds slot s's logical block j
    k_pool = k_lane.reshape(B, H, mb, bl, d).transpose(0, 2, 1, 3, 4) \
        .reshape(B * mb, H, bl, d)
    v_pool = v_lane.reshape(B, H, mb, bl, d).transpose(0, 2, 1, 3, 4) \
        .reshape(B * mb, H, bl, d)
    table = jnp.asarray(
        [[s * mb + j for j in range(mb)] for s in range(B)], jnp.int32)
    dense = kv_cache.cached_attention(q, k_lane, v_lane, lengths)
    paged = kv_cache.paged_cached_attention(q, k_pool, v_pool, lengths,
                                            table, block_len=bl)
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))


@pytest.mark.parametrize("lengths", [[0, 1, 5], [15, 16, 17],
                                     [32, 47, 63]])
def test_paged_flash_decode_matches_composed_golden(lengths):
    """The paged flash kernel (CPU ``interpret=True``) equals the
    composed gather+masked-attention fallback across block-boundary
    edge lengths: shorter than one block, exactly on a boundary, one
    past it, and the full padded extent."""
    from autodist_tpu.kernel.pallas.flash_decode import \
        flash_decode_attention_paged

    rng = np.random.RandomState(1)
    B, H, d, bl, nb, mb = 3, 2, 8, 16, 13, 4
    k_pool = jnp.asarray(rng.randn(nb, H, bl, d), jnp.float32)
    v_pool = jnp.asarray(rng.randn(nb, H, bl, d), jnp.float32)
    q = jnp.asarray(rng.randn(B, 1, H, d), jnp.float32)
    table = jnp.asarray(rng.randint(0, nb, (B, mb)), jnp.int32)
    L = jnp.asarray(lengths, jnp.int32)
    ref = kv_cache.paged_cached_attention(q, k_pool, v_pool, L, table,
                                          block_len=bl)
    got = flash_decode_attention_paged(q, k_pool, v_pool, L, table,
                                       block_len=bl, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_paged_write_respects_write_mask():
    """An inactive slot's table row points at block 0 — possibly
    another slot's live block — so suppressed writes must keep the
    target row bit-for-bit."""
    c = kv_cache.init_paged_cache(1, 2, 2, 3, max_len=8, block_len=4,
                                  num_blocks=4)
    resident = c.k + 7.0
    kv = jnp.ones((2, 1, 2, 3), jnp.float32)
    table = jnp.asarray([[1, 2], [0, 0]], jnp.int32)   # slot1 unmapped
    positions = jnp.asarray([0, 0], jnp.int32)
    mask = jnp.asarray([True, False])
    k = kv_cache.paged_write_token(resident, 0, kv, positions, table, 4,
                                   write_mask=mask)
    # active slot 0's row landed in its block 1
    np.testing.assert_array_equal(np.asarray(k[0, 1, :, 0, :]),
                                  np.ones((2, 3)))
    # inactive slot 1's write into block 0 was suppressed entirely
    np.testing.assert_array_equal(np.asarray(k[0, 0]),
                                  np.asarray(resident[0, 0]))


# --------------------------------------------------------------------- #
# greedy parity goldens: paged == dense token-for-token
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("tp,vocab_parallel", [(1, False), (2, False),
                                               (2, True)])
def test_paged_greedy_matches_dense(cfg, params, tp, vocab_parallel):
    """Paged decode (non-divisible block_len 5 against max_len 24, so
    every request crosses a partial tail block) equals the dense engine
    token-for-token across tp∈{1,2} × vocab-parallel, V=33 odd."""
    reqs = [(PROMPT, 9), ([2, 7, 1], 6)]

    def run(kv_layout, **kw):
        b = ContinuousBatcher(make_engine(
            cfg, params, tp=tp, vocab_parallel=vocab_parallel,
            kv_layout=kv_layout, **kw))
        rids = [b.submit(p, max_new_tokens=m) for p, m in reqs]
        done = b.run()
        return [done[r].tokens for r in rids]

    dense = run("dense")
    paged = run("paged", kv_block_len=5)
    assert paged == dense
    assert all(0 <= t < cfg.vocab_size for toks in paged for t in toks)


def test_paged_block_recycling_mid_stream(cfg, params):
    """The eviction/re-admission edge: a pool too small for all
    requests at once forces later requests to wait for freed blocks and
    decode into them MID-STREAM of the survivors — every request still
    matches its run-alone tokens."""
    # 6-block pool of block_len 8; each request spans 2 blocks
    # (prompt 5 + budget 8 = 13) -> at most 3 in flight, requests 4-5
    # admit only into recycled blocks while earlier slots keep decoding.
    reqs = [(PROMPT, 8), ([2, 7, 1], 10), ([5, 5, 5, 5, 9], 7),
            ([1, 2, 3], 9), ([8, 6, 7], 11)]
    eng = make_engine(cfg, params, kv_layout="paged", slots=5,
                      kv_block_len=8, kv_num_blocks=6)
    b = ContinuousBatcher(eng)
    rids = [b.submit(p, max_new_tokens=m) for p, m in reqs]
    inter = b.run()
    assert eng.free_blocks == 6           # all blocks returned
    for (p, m), rid in zip(reqs, rids):
        solo = ContinuousBatcher(make_engine(
            cfg, params, kv_layout="paged", slots=5, kv_block_len=8,
            kv_num_blocks=6))
        srid = solo.submit(p, max_new_tokens=m)
        assert inter[rid].tokens == solo.run()[srid].tokens, rid


def test_paged_max_len_eviction(cfg, params):
    """The over-budget truncation edge rides the paged layout too: the
    clamped tail write lands in the slot's own tail block (never block
    0), so a concurrent short request's tokens are unperturbed."""
    b = ContinuousBatcher(make_engine(cfg, params, kv_layout="paged",
                                      kv_block_len=5))
    rid = b.submit(PROMPT, max_new_tokens=200)
    short = b.submit([2, 7], max_new_tokens=3)
    done = b.run()
    assert done[rid].finish_reason == "max_len"
    assert len(done[rid].tokens) == cfg.max_len - len(PROMPT)
    solo = ContinuousBatcher(make_engine(cfg, params))
    srid = solo.submit([2, 7], max_new_tokens=3)
    assert done[short].tokens == solo.run()[srid].tokens


# --------------------------------------------------------------------- #
# block-aware admission: free blocks, not slots
# --------------------------------------------------------------------- #
def test_short_mix_capacity_paged_beats_dense(cfg, params):
    """At EQUAL pool bytes (2 full max_len lanes == 6 blocks of 8), a
    short-request mix reaches strictly higher peak concurrency under
    paged admission than the dense slot ceiling — the ISSUE 14
    acceptance capacity claim."""
    reqs = [([2, 3], 4)] * 6                       # span 6 -> 1 block

    def peak(engine):
        b = ContinuousBatcher(engine)
        for p, m in reqs:
            b.submit(p, max_new_tokens=m)
        peak = 0
        while b._queue or b.active_slots:
            b.step()
            peak = max(peak, b.active_slots)
        return peak

    dense_peak = peak(make_engine(cfg, params, slots=2))
    paged_peak = peak(make_engine(cfg, params, kv_layout="paged",
                                  slots=6, kv_block_len=8,
                                  kv_num_blocks=6))
    assert dense_peak == 2                          # slot-bound
    assert paged_peak > dense_peak                  # block-bound: 6


def test_admission_gates_on_free_blocks_head_of_line(cfg, params):
    """A head request too big for the current free pool WAITS (no
    queue-jumping — admission order stays deterministic) and the
    engine's reserve path is never driven into PoolExhaustedError."""
    eng = make_engine(cfg, params, kv_layout="paged", slots=4,
                      kv_block_len=8, kv_num_blocks=3)
    b = ContinuousBatcher(eng)
    big = b.submit(PROMPT, max_new_tokens=18)      # 23 -> 3 blocks
    small = b.submit([2, 7], max_new_tokens=4)     # 6 -> 1 block
    b.step()                                       # one admission round
    # the whole pool went to the head request; the small one queued
    # even though 3 slots are free
    assert b.active_slots == 1 and len(b._queue) == 1
    assert eng.free_blocks == 0
    done = b.run()
    assert set(done) == {big, small}
    assert eng.free_blocks == 3


def test_cache_block_table_mirrors_live_reservations(cfg, params):
    """The device-side ``engine.cache.block_table`` is the complete
    decode state, not a stale zeros placeholder: it reflects every
    reserve/release the moment it happens (a consumer serializing the
    cache pytree between dispatches — elastic checkpointing, debug
    dumps — must see the real mapping), and it is the SAME array the
    compiled programs consume."""
    eng = make_engine(cfg, params, kv_layout="paged", slots=3,
                      kv_block_len=8, kv_num_blocks=6)
    assert np.all(np.asarray(eng.cache.block_table) == 0)
    eng.reserve_slot(1, 5, 8)                  # 13 -> 2 blocks
    np.testing.assert_array_equal(np.asarray(eng.cache.block_table),
                                  eng._table)
    assert np.any(np.asarray(eng.cache.block_table)[1] != 0)
    assert eng._table_arg() is eng.cache.block_table
    eng.release_slot(1)
    np.testing.assert_array_equal(np.asarray(eng.cache.block_table),
                                  np.zeros_like(eng._table))


def test_engine_reserve_release_accounting(cfg, params):
    eng = make_engine(cfg, params, kv_layout="paged", slots=3,
                      kv_block_len=8, kv_num_blocks=6)
    assert eng.blocks_needed(5, 8) == 2            # 13 -> 2 blocks
    assert eng.blocks_needed(5, 200) == 3          # clamped at max_len
    eng.reserve_slot(0, 5, 8)
    assert eng.free_blocks == 4
    with pytest.raises(ValueError, match="already holds"):
        eng.reserve_slot(0, 2, 2)
    eng.release_slot(0)
    assert eng.free_blocks == 6
    eng.release_slot(0)                            # idempotent
    assert eng.free_blocks == 6
    # dense: the predicate is vacuous
    dense = make_engine(cfg, params)
    assert dense.blocks_needed(5, 8) == 0 and dense.free_blocks == 0


# --------------------------------------------------------------------- #
# engine config validation + Strategy-IR seeding
# --------------------------------------------------------------------- #
def test_engine_validates_kv_layout(cfg, params):
    from autodist_tpu.strategy.ir import UnknownKVLayoutError

    with pytest.raises(UnknownKVLayoutError, match="blocked"):
        make_engine(cfg, params, kv_layout="blocked")
    with pytest.raises(ValueError, match="cannot hold even one"):
        make_engine(cfg, params, kv_layout="paged", kv_block_len=8,
                    kv_num_blocks=2)               # max_len 24 -> 3
    with pytest.raises(ValueError, match="temperature"):
        make_engine(cfg, params, temperature=-0.1)
    with pytest.raises(ValueError, match="top_k"):
        make_engine(cfg, params, top_k=-1)


def test_seed_engine_kwargs_threads_kv_layout():
    from autodist_tpu.strategy.ir import GraphConfig, Strategy

    strategy = Strategy(node_configs=[], graph_config=GraphConfig(
        replicas=1, lowering="pipeline",
        parallel={"tensor_parallel": 1, "kv_layout": "paged"}))
    kwargs = seed_engine_kwargs({}, strategy)
    assert kwargs["kv_layout"] == "paged"
    # pre-PR-14 strategies (no knob) seed dense
    old = Strategy(node_configs=[], graph_config=GraphConfig(
        replicas=1, lowering="pipeline", parallel={}))
    assert seed_engine_kwargs({}, old)["kv_layout"] == "dense"


def test_normalize_kv_layout_contract():
    from autodist_tpu.strategy.ir import (UnknownKVLayoutError,
                                          normalize_kv_layout)

    assert normalize_kv_layout(None) == "dense"
    assert normalize_kv_layout("") == "dense"
    assert normalize_kv_layout("paged") == "paged"
    with pytest.raises(UnknownKVLayoutError):
        normalize_kv_layout("vllm")


# --------------------------------------------------------------------- #
# the sampling rung: temperature/top_k with interleave parity
# --------------------------------------------------------------------- #
def _sample_stream(cfg, params, *, interleaved, tp=1,
                   vocab_parallel=False, kv_layout="dense",
                   temperature=0.8, top_k=5, seed=11):
    b = ContinuousBatcher(make_engine(
        cfg, params, tp=tp, vocab_parallel=vocab_parallel,
        kv_layout=kv_layout, temperature=temperature, top_k=top_k))
    rid = b.submit(PROMPT, max_new_tokens=7, seed=seed)
    if interleaved:
        b.submit([2, 7], max_new_tokens=5, seed=99)
    return b.run()[rid].tokens


def test_sampled_interleave_parity(cfg, params):
    """A sampled stream keyed per (request seed, context length) is
    identical run-alone, interleaved, under tp=2 × vocab-parallel, and
    under the paged layout — the interleave-parity contract extended to
    sampling."""
    alone = _sample_stream(cfg, params, interleaved=False)
    assert alone == _sample_stream(cfg, params, interleaved=True)
    assert alone == _sample_stream(cfg, params, interleaved=True, tp=2,
                                   vocab_parallel=True)
    assert alone == _sample_stream(cfg, params, interleaved=True,
                                   kv_layout="paged")
    assert all(0 <= t < cfg.vocab_size for t in alone)


def test_sampled_streams_vary_by_seed_and_temperature(cfg, params):
    base = _sample_stream(cfg, params, interleaved=False, seed=11)
    other = _sample_stream(cfg, params, interleaved=False, seed=12)
    hot = _sample_stream(cfg, params, interleaved=False, seed=11,
                         temperature=5.0, top_k=0)
    assert base != other or base != hot   # sampling actually samples


def test_temperature_zero_is_bit_identical_to_greedy(cfg, params):
    """temperature == 0 compiles the exact pre-sampling program (the
    sampler is never traced), so the tokens ARE the greedy goldens —
    whatever seed the request carries."""
    greedy = ContinuousBatcher(make_engine(cfg, params))
    g = greedy.submit(PROMPT, max_new_tokens=9)
    want = greedy.run()[g].tokens
    t0 = ContinuousBatcher(make_engine(cfg, params, temperature=0.0))
    rid = t0.submit(PROMPT, max_new_tokens=9, seed=123)
    assert t0.run()[rid].tokens == want


def test_top_k_one_recovers_greedy_at_any_temperature(cfg, params):
    """top_k=1 restricts sampling to the argmax row, so even at a high
    temperature the stream equals the greedy tokens — the sampler's
    distributional clamp, pinned across tp and the paged layout."""
    greedy = ContinuousBatcher(make_engine(cfg, params))
    g = greedy.submit(PROMPT, max_new_tokens=7)
    want = greedy.run()[g].tokens
    for kw in ({}, {"tp": 2, "vocab_parallel": True},
               {"kv_layout": "paged"}):
        got = _sample_stream(cfg, params, interleaved=False,
                             temperature=5.0, top_k=1, **kw)
        assert got == want, kw


def test_sampler_rejects_temperature_zero():
    from autodist_tpu.parallel.tensor import vocab_parallel_sample_token

    with pytest.raises(ValueError, match="greedy"):
        vocab_parallel_sample_token(
            jnp.zeros((1, 4)), jnp.zeros((8, 4)), vocab_size=8,
            seeds=jnp.zeros((1,), jnp.int32),
            positions=jnp.zeros((1,), jnp.int32), temperature=0.0)


# --------------------------------------------------------------------- #
# the cost model's capacity objective (election pinned both ways)
# --------------------------------------------------------------------- #
def test_decode_cost_elects_paged_exactly_when_variance_pays():
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import CostModel

    trainable = make_pipeline_lm_trainable(
        make_cfg(vocab=512, max_len=64), optax.sgd(0.1),
        jax.random.PRNGKey(0))
    rs = ResourceSpec({"topology": {"platform": "cpu",
                                    "num_devices": 2}})
    cm = CostModel(rs)
    # short-request mix: paged's per-request residency is ~1 block
    # instead of the max_len lane -> capacity multiplies
    dense = cm.decode_cost(trainable, {"tensor_parallel": 1},
                           max_len=2048, mean_request_len=64.0)
    paged = cm.decode_cost(trainable, {"tensor_parallel": 1,
                                       "kv_layout": "paged"},
                           max_len=2048, mean_request_len=64.0)
    assert paged.request_capacity > dense.request_capacity
    assert paged.serve_score < dense.serve_score       # paged elected
    # latency side still pays the table indirection
    assert paged.token_time_s > dense.token_time_s
    # no-variance mix: capacities tie (block-rounded), the indirection
    # overhead decides -> dense elected
    d2 = cm.decode_cost(trainable, {"tensor_parallel": 1},
                        max_len=2048, mean_request_len=2048.0)
    p2 = cm.decode_cost(trainable, {"tensor_parallel": 1,
                                    "kv_layout": "paged"},
                        max_len=2048, mean_request_len=2048.0)
    assert p2.serve_score > d2.serve_score             # dense elected


def test_rank_serving_capacity_objective_both_ways():
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import rank_serving

    trainable = make_pipeline_lm_trainable(
        make_cfg(vocab=512, max_len=64), optax.sgd(0.1),
        jax.random.PRNGKey(0))
    rs = ResourceSpec({"topology": {"platform": "cpu",
                                    "num_devices": 2}})
    short = rank_serving(trainable, rs, objective="capacity",
                         mean_request_len=64.0, max_len=2048)
    assert short[0][0].get("kv_layout") == "paged"
    uniform = rank_serving(trainable, rs, objective="capacity",
                           mean_request_len=2048.0, max_len=2048)
    assert uniform[0][0].get("kv_layout", "dense") == "dense"
    # the latency objective ignores capacity and keeps dense first
    # (paged only pays the indirection there)
    latency = rank_serving(trainable, rs, max_len=2048)
    assert latency[0][0].get("kv_layout", "dense") == "dense"
    with pytest.raises(ValueError, match="objective"):
        rank_serving(trainable, rs, objective="throughput")


def test_default_serving_candidates_carry_layouts():
    from autodist_tpu.simulator.auto_strategy import \
        default_serving_candidates

    cands = default_serving_candidates(2)
    layouts = {(c.get("tensor_parallel"), c.get("kv_layout", "dense"))
               for c in cands}
    assert (1, "dense") in layouts and (1, "paged") in layouts
    assert (2, "paged") in layouts
    # a dense candidate carries NO kv_layout key: its JSON round-trips
    # byte-identically to a pre-PR-14 config
    assert all("kv_layout" not in c or c["kv_layout"] != "dense"
               for c in cands)


# --------------------------------------------------------------------- #
# program lint: the ADT115 paged-cache rule (mutations ride the
# test_analysis matrix; here the honest programs + derivation)
# --------------------------------------------------------------------- #
def test_rules_for_decode_derive_paged_contract():
    from autodist_tpu.analysis import rules_for_decode

    paged = rules_for_decode(1, False, vocab_size=93, max_len=57,
                             num_layers=2, num_slots=3, heads_local=2,
                             head_dim=8, kv_layout="paged",
                             pool_blocks=13)
    codes = {r.code for r in paged}
    assert "ADT115" in codes
    dense = rules_for_decode(1, False, vocab_size=93, max_len=57,
                             num_layers=2, num_slots=3, heads_local=2,
                             head_dim=8)
    assert "ADT115" not in {r.code for r in dense}
    # flash-elected paged: the rule stays but its gather half is off
    # (the page walk lives inside the Pallas kernel)
    flash = rules_for_decode(1, False, vocab_size=93, max_len=57,
                             num_layers=2, num_slots=3, heads_local=2,
                             head_dim=8, kv_layout="paged",
                             pool_blocks=13, kernel=("flash_decode",))
    fr = [r for r in flash if r.code == "ADT115"]
    assert len(fr) == 1


def test_paged_decode_program_is_lint_clean():
    """The compiled paged decode program carries ZERO dense
    [slots x max_len] cache buffers and >= 1 block-table gather — the
    ISSUE 14 acceptance structure, on the real program."""
    from autodist_tpu.analysis import lint_program, rules_for_decode
    from autodist_tpu.analysis import programs

    text = programs.decode_step_text(1, False, kv_layout="paged")
    rules = rules_for_decode(
        1, False, vocab_size=programs.DEC_V, max_len=programs.DEC_T,
        num_layers=programs.DEC_LAYERS, num_slots=programs.DEC_SLOTS,
        heads_local=2, head_dim=programs.DEC_HEAD_DIM,
        kv_layout="paged", pool_blocks=programs.DEC_POOL_BLOCKS)
    report = lint_program(text, rules, where="decode/paged")
    assert not report.errors, [d.to_dict() for d in report.errors]
    # and the dense sibling DOES carry the lane the rule forbids
    from autodist_tpu.analysis.facts import ProgramFacts
    dense_facts = ProgramFacts.from_hlo(
        programs.decode_step_text(1, False))
    assert dense_facts.buffers_with_dims(
        (programs.DEC_SLOTS, programs.DEC_T)) > 0


# --------------------------------------------------------------------- #
# telemetry: pool gauges + kv_layout record field, schema-gated
# --------------------------------------------------------------------- #
def test_paged_telemetry_gauges_and_schema_gate(cfg, params, tmp_path):
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path), enabled=True)
    try:
        b = ContinuousBatcher(make_engine(cfg, params,
                                          kv_layout="paged",
                                          kv_block_len=8))
        rid = b.submit(PROMPT, max_new_tokens=4)
        b.run()
        paths = telemetry.flush()
    finally:
        telemetry.reset()
    with open(paths["metrics"]) as f:
        recs = [json.loads(line) for line in f]
    serve = next(r for r in recs if r.get("kind") == "serve")
    assert serve["request"] == rid
    assert serve["kv_layout"] == "paged"
    gauges = {r["name"]: r["value"] for r in recs
              if r.get("kind") == "gauge"}
    assert "serve/kv_blocks_free" in gauges
    assert "serve/kv_blocks_used" in gauges
    assert gauges["serve/kv_blocks_used"] == 0     # all released

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    assert telemetry_report.check_schema(str(tmp_path)) == []
    md = telemetry_report.render(str(tmp_path))
    assert "paged" in md and "kv block pool" in md

    # a paged run stripped of its pool gauges fails the CI gate
    metrics = os.path.join(tmp_path, "metrics.jsonl")
    with open(metrics) as f:
        kept = [line for line in f
                if "serve/kv_blocks" not in line]
    with open(metrics, "w") as f:
        f.writelines(kept)
    problems = telemetry_report.check_schema(str(tmp_path))
    assert any("kv_blocks" in p for p in problems)


def test_dense_run_passes_schema_without_pool_gauges(cfg, params,
                                                     tmp_path):
    """Dense runs carry kv_layout="dense" and owe no pool gauges."""
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path), enabled=True)
    try:
        b = ContinuousBatcher(make_engine(cfg, params))
        b.submit(PROMPT, max_new_tokens=3)
        b.run()
        telemetry.flush()
    finally:
        telemetry.reset()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    assert telemetry_report.check_schema(str(tmp_path)) == []
