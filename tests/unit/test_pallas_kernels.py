"""The Pallas fused-kernel tier (PR 13).

Interpreter-mode goldens for all three kernels against their composed
lowerings across edge shapes (non-divisible block sizes, slot lengths
shorter than one block, V % tp != 0 vocab padding, all-zero quantize
blocks), the Strategy-IR kernel-slot round trip (pre-PR-13 JSON lowers
byte-identically with the slot absent), both-directions election per
link/kernel profile (training search AND serving decode), the serving
engine's attention_fn gate, the ADT090/ADT120 rules, and the telemetry
kernel-gauge schema gate.

Kernel modules are imported inside tests (conftest guard: Pallas
modules are never top-level imports in a tier-1 module); shapes stay
tiny so the interpreter runs in seconds.
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import AutoDist
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.strategy.ir import (Strategy, UnknownKernelError,
                                      normalize_kernel)

TP_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 2, "pipe": 2, "model": 2}}


def _lm_cfg(**kw):
    from autodist_tpu.models.transformer import TransformerConfig

    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_heads=2, mlp_dim=32, max_len=8, dtype=jnp.float32,
                dropout_rate=0.0, attention_dropout_rate=0.0)
    base.update(kw)
    return TransformerConfig(**base)


def _lm_trainable(cfg):
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable

    return make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                      jax.random.PRNGKey(0))


def _lm_batch(vocab, batch=8, length=8, seed=0):
    r = np.random.RandomState(seed)
    return {"x": r.randint(0, vocab, (batch, length)).astype(np.int32),
            "y": r.randint(0, vocab, (batch, length)).astype(np.int32)}


# --------------------------------------------------------------------------- #
# Kernel goldens vs the composed lowerings (interpreter mode)
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("lengths,block_k", [
    ([0, 3, 56], 16),      # slot shorter than one block + near-full
    ([1, 15, 16], 16),     # block-boundary edges
    ([55, 2, 30], 13),     # T=57 non-divisible by block 13
])
def test_flash_decode_golden_vs_cached_attention(lengths, block_k):
    from autodist_tpu.kernel.pallas.flash_decode import \
        flash_decode_attention
    from autodist_tpu.serving.kv_cache import cached_attention

    B, H, T, d = 3, 2, 57, 8
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, 1, H, d), jnp.float32)
    k = jnp.asarray(r.randn(B, H, T, d), jnp.float32)
    v = jnp.asarray(r.randn(B, H, T, d), jnp.float32)
    lens = jnp.asarray(lengths, jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = flash_decode_attention(q, k, v, lens, block_k=block_k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("n,size", [(2, 37), (4, 64), (2, 8)])
def test_quant_ring_golden(n, size):
    """The fused-q/dq ring reproduces its arithmetic mirror (per-hop
    requantization included) and stays within int8 tolerance of the
    exact fp32 sum; payload sizes that don't divide the ring exercise
    the zero-pad path."""
    from autodist_tpu.kernel.pallas.quant_ring import (
        quantized_ring_all_reduce, reference_ring_all_reduce)

    mesh = Mesh(np.array(jax.devices()[:n]), ("model",))
    r = np.random.RandomState(0)
    xs = jnp.asarray(r.randn(n, size), jnp.float32)
    sm = jax.jit(jax.shard_map(
        lambda x: quantized_ring_all_reduce(x, "model"), mesh=mesh,
        in_specs=P("model"), out_specs=P("model"), check_vma=False))
    got = sm(xs)
    refs = reference_ring_all_reduce(list(xs))
    for i in range(n):
        np.testing.assert_allclose(np.asarray(got[i]),
                                   np.asarray(refs[i]), atol=1e-6)
    true_sum = np.asarray(jnp.sum(xs, 0))
    scale = np.abs(true_sum).max()
    for i in range(n):
        np.testing.assert_allclose(np.asarray(got[i]), true_sum,
                                   atol=0.1 * scale)


def test_quant_ring_all_zero_block():
    from autodist_tpu.kernel.pallas.quant_ring import \
        quantized_ring_all_reduce

    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    xs = jnp.zeros((2, 16), jnp.float32)
    sm = jax.jit(jax.shard_map(
        lambda x: quantized_ring_all_reduce(x, "model"), mesh=mesh,
        in_specs=P("model"), out_specs=P("model"), check_vma=False))
    assert float(jnp.max(jnp.abs(sm(xs)))) == 0.0


@pytest.mark.parametrize("xs,ks,axes,specs", [
    ((4, 6), (6, 10), 1, (P(None, "model"), P("model", None))),
    ((4, 6), (6, 16), 1, (P(None, "model"), P("model", None))),
    # axes=2 (the attention out-proj shape) with width 7 % tp != 0
    ((4, 2, 4), (2, 4, 7), 2,
     (P(None, "model", None), P("model", None, None))),
])
def test_collective_matmul_fused_golden(xs, ks, axes, specs):
    """Fused ring step == composed collective_matmul_row bit-for-bit
    (same arithmetic, one kernel pass), gradients included."""
    from autodist_tpu.kernel.pallas.collective_matmul import \
        collective_matmul_row_fused
    from autodist_tpu.parallel.tensor import collective_matmul_row

    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(*xs), jnp.float32)
    kern = jnp.asarray(r.randn(*ks), jnp.float32)

    def run(fn):
        def g(xl, kl):
            return fn(xl, kl, "model", axes)
        return jax.jit(jax.shard_map(g, mesh=mesh, in_specs=specs,
                                     out_specs=P(), check_vma=False))

    comp = run(collective_matmul_row)(x, kern)
    fused = run(collective_matmul_row_fused)(x, kern)
    np.testing.assert_array_equal(np.asarray(comp), np.asarray(fused))

    def grads(fn):
        def g(xl, kl):
            return fn(xl, kl, "model", axes)
        sm = jax.shard_map(g, mesh=mesh, in_specs=specs, out_specs=P(),
                           check_vma=False)
        return jax.jit(jax.grad(lambda a, b: jnp.sum(sm(a, b) ** 2),
                                argnums=(0, 1)))(x, kern)

    for a, b in zip(grads(collective_matmul_row),
                    grads(collective_matmul_row_fused)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------------- #
# Strategy IR: the kernel slot
# --------------------------------------------------------------------------- #
def test_normalize_kernel_forms_and_rejects():
    assert normalize_kernel(None) == {}
    assert normalize_kernel({}) == {}
    assert normalize_kernel("quant_ring") == {"quant_ring": True}
    assert normalize_kernel(("collective_matmul", "flash_decode")) == {
        "flash_decode": True, "collective_matmul": True}
    assert normalize_kernel({"quant_ring": False}) == {}
    with pytest.raises(UnknownKernelError):
        normalize_kernel("warp_drive")
    with pytest.raises(UnknownKernelError):
        Strategy.from_json(json.dumps({
            "id": "x", "node_configs": [],
            "graph_config": {"kernel": {"warp_drive": True}}}))


def test_kernel_slot_round_trips_and_pre_pr13_json_is_composed():
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    spec = ResourceSpec(TP_SPEC)
    s = Pipeline(num_microbatches=2, tensor_parallel=2,
                 collective_precision={"tp_psum": "int8"},
                 kernel=("quant_ring",)).build(tr, spec)
    clone = Strategy.from_json(s.to_json())
    assert clone.graph_config.kernel == {"quant_ring": True}
    # A pre-PR-13 JSON (no kernel key at all) deserializes to the
    # composed lowering.
    d = json.loads(s.to_json())
    del d["graph_config"]["kernel"]
    old = Strategy.from_json(json.dumps(d))
    assert old.graph_config.kernel == {}


def test_pre_pr13_json_lowers_byte_identically():
    """Stripping the (empty) kernel slot from a serialized strategy
    changes nothing about the compiled program — the slot is additive."""
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    spec = ResourceSpec(TP_SPEC)
    s = Pipeline(num_microbatches=2, tensor_parallel=2).build(tr, spec)
    d = json.loads(s.to_json())
    assert d["graph_config"]["kernel"] == {}
    del d["graph_config"]["kernel"]
    old = Strategy.from_json(json.dumps(d))
    batch = _lm_batch(cfg.vocab_size)

    def text_of(strategy):
        runner = AutoDist(TP_SPEC, "AllReduce").build(tr, strategy)
        try:
            return runner.lowered.step_fn.lower(
                runner.state, runner._place_batch(batch),
                jax.random.PRNGKey(0)).compile().as_text()
        finally:
            runner.close()

    assert text_of(s) == text_of(old)


def test_builder_rejects_kernel_without_enabling_knob():
    from autodist_tpu.strategy.parallel_builders import Pipeline

    with pytest.raises(ValueError, match="quant_ring"):
        Pipeline(tensor_parallel=2, kernel=("quant_ring",))
    with pytest.raises(ValueError, match="quant_ring"):
        Pipeline(tensor_parallel=2,
                 collective_precision={"tp_psum": "int8"},
                 comm_overlap="rsag", kernel=("quant_ring",))
    with pytest.raises(ValueError, match="collective_matmul"):
        Pipeline(tensor_parallel=2, kernel=("collective_matmul",))


def test_plan_lint_adt090_fires_on_hand_edit_and_stays_silent():
    from autodist_tpu.analysis import lint_plan
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    spec = ResourceSpec(TP_SPEC)
    s = Pipeline(num_microbatches=2, tensor_parallel=2,
                 collective_precision={"tp_psum": "int8"},
                 kernel=("quant_ring",)).build(tr, spec)
    clean = lint_plan(s, resource_spec=spec, trainable=tr)
    assert "ADT090" not in clean.codes()
    d = json.loads(s.to_json())
    d["graph_config"]["precision"] = {}
    mutated = lint_plan(Strategy.from_json(json.dumps(d)),
                        resource_spec=spec, trainable=tr)
    assert "ADT090" in mutated.codes()


# --------------------------------------------------------------------------- #
# Training goldens: kernel-elected steps track the composed siblings
# --------------------------------------------------------------------------- #
def _train_losses(tr_factory, batch, steps=3, **autodist_kw):
    runner = AutoDist(TP_SPEC, "Pipeline", num_microbatches=2,
                      **autodist_kw).build(tr_factory())
    try:
        return [float(np.asarray(runner.step(batch)["loss"]))
                for _ in range(steps)]
    finally:
        runner.close()


def test_quant_ring_training_tracks_composed_int8():
    """The ring-elected trajectory stays within the int8-vs-composed
    tolerance of the composed int8 program (per-hop requantization is
    the only numeric difference)."""
    cfg = _lm_cfg()
    batch = _lm_batch(cfg.vocab_size)
    make = lambda: _lm_trainable(cfg)   # noqa: E731
    composed = _train_losses(make, batch, tensor_parallel=2,
                             collective_precision={"tp_psum": "int8"})
    ring = _train_losses(make, batch, tensor_parallel=2,
                         collective_precision={"tp_psum": "int8"},
                         kernel=("quant_ring",))
    np.testing.assert_allclose(ring, composed, rtol=2e-2)


def test_collective_matmul_training_matches_composed():
    """The fused ring step is the same arithmetic — trajectories are
    bit-close to the composed matmul-overlap program."""
    cfg = _lm_cfg()
    batch = _lm_batch(cfg.vocab_size)
    make = lambda: _lm_trainable(cfg)   # noqa: E731
    composed = _train_losses(make, batch, tensor_parallel=2,
                             comm_overlap="matmul")
    fused = _train_losses(make, batch, tensor_parallel=2,
                          comm_overlap="matmul",
                          kernel=("collective_matmul",))
    np.testing.assert_allclose(fused, composed, rtol=1e-5)


def test_quant_ring_with_vocab_padding():
    """V % tp != 0: the vocab-parallel prologue's lookup psum rides the
    ring too (it IS a sum_partials boundary) over zero-padded rows."""
    cfg = _lm_cfg(vocab_size=33)
    batch = _lm_batch(33)
    make = lambda: _lm_trainable(cfg)   # noqa: E731
    composed = _train_losses(make, batch, tensor_parallel=2,
                             vocab_parallel=True,
                             collective_precision={"tp_psum": "int8"})
    ring = _train_losses(make, batch, tensor_parallel=2,
                         vocab_parallel=True,
                         collective_precision={"tp_psum": "int8"},
                         kernel=("quant_ring",))
    np.testing.assert_allclose(ring, composed, rtol=2e-2)


# --------------------------------------------------------------------------- #
# ADT120: the fused-kernel program proof (both ways)
# --------------------------------------------------------------------------- #
def test_adt120_discriminates_ring_program_from_composed_sibling():
    from autodist_tpu.analysis import lint_program, programs
    from autodist_tpu.analysis.program_rules import fused_kernel_replaced

    honest = programs.pipeline_step_text(
        2, collective_precision=(("tp_psum", "int8"),),
        kernel=("quant_ring",))
    sibling = programs.pipeline_step_text(
        2, collective_precision=(("tp_psum", "int8"),))
    rules = [fused_kernel_replaced(("quant_ring",), tp=2)]
    assert not lint_program(honest, rules).errors
    assert lint_program(sibling, rules).by_code("ADT120")


def test_adt120_discriminates_flash_decode_from_composed_sibling():
    from autodist_tpu.analysis import lint_program, programs
    from autodist_tpu.analysis.program_rules import fused_kernel_replaced

    honest = programs.decode_step_text(1, False,
                                       kernel=("flash_decode",))
    sibling = programs.decode_step_text(1, False)
    rules = [fused_kernel_replaced(("flash_decode",), tp=1)]
    assert not lint_program(honest, rules).errors
    assert lint_program(sibling, rules).by_code("ADT120")


def test_adt120_holds_on_honest_tp4_ring():
    """Regression: the ring kernels drive their hops with an unrolled
    python loop, NOT lax.scan — a scanned ring prints each ppermute
    once inside an HLO while loop, so at tp >= 4 (where the trip count
    survives loop simplification) ADT120's 2(tp-1) s8-permute evidence
    would falsely report the wire missing on a program where the
    kernel genuinely ran."""
    from autodist_tpu.analysis import lint_program
    from autodist_tpu.analysis.program_rules import fused_kernel_replaced
    from autodist_tpu.analysis.programs import compiled_text
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg(num_heads=4)
    spec = ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8},
                         "mesh": {"data": 1, "pipe": 2, "model": 4}})
    batch = _lm_batch(cfg.vocab_size)
    auto = AutoDist(spec, Pipeline(
        num_microbatches=2, tensor_parallel=4,
        collective_precision={"tp_psum": "int8"},
        kernel=("quant_ring",)))
    runner = auto.build(_lm_trainable(cfg))
    try:
        honest = compiled_text(runner.lowered.step_fn, runner.state,
                               runner._place_batch(batch),
                               jax.random.PRNGKey(0))
    finally:
        runner.close()
    res = lint_program(honest,
                       [fused_kernel_replaced(("quant_ring",), tp=4)])
    assert not res.errors, res.errors


# --------------------------------------------------------------------------- #
# Election: the search picks a kernel exactly when the profile favors it
# --------------------------------------------------------------------------- #
def _ring_strategies():
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    tr.tokens_per_step = 64 * 512          # comm-heavy activation hint
    spec = ResourceSpec(TP_SPEC)
    composed = Pipeline(num_microbatches=2, tensor_parallel=2,
                        collective_precision={"tp_psum": "int8"}
                        ).build(tr, spec)
    ring = Pipeline(num_microbatches=2, tensor_parallel=2,
                    collective_precision={"tp_psum": "int8"},
                    kernel=("quant_ring",)).build(tr, spec)
    return tr, spec, composed, ring


def test_quant_ring_election_pinned_both_directions():
    from autodist_tpu.simulator.cost_model import CostModel

    tr, spec, composed, ring = _ring_strategies()
    # Comm-bound: a slow wire makes the 2x byte saving dominate the
    # extra q/dq passes — the ring must win.
    slow = CostModel(spec, link_profile={"ici_gbps": 0.05},
                     quant_profile={"int8_s_per_elem": 1e-12})
    assert slow.strategy_cost(tr, ring).comm_time_s \
        < slow.strategy_cost(tr, composed).comm_time_s
    # Compute-bound: a fast wire with expensive per-hop requantization
    # flips it — the composed sandwich must win.
    fast = CostModel(spec, link_profile={"ici_gbps": 1e5},
                     quant_profile={"int8_s_per_elem": 1e-7},
                     kernel_profile={"quant_ring_qdq_factor": 4.0})
    assert fast.strategy_cost(tr, ring).comm_time_s \
        > fast.strategy_cost(tr, composed).comm_time_s


def test_search_elects_kernel_candidate_exactly_when_favored():
    """AutoStrategy(search=True)'s frontier (search_strategies is the
    engine under it) ranks a kernel-backed candidate first exactly when
    the calibrated profile favors it — pinned both directions."""
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.simulator.search import (SearchSpace,
                                               search_strategies)

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    tr.tokens_per_step = 64 * 512
    spec = ResourceSpec(TP_SPEC)
    space = SearchSpace(tp=(2,), num_microbatches=(2,),
                        vocab_parallel=(False,), zero_stage=(0,),
                        comm_overlap=(None,),
                        collective_precision=("int8",),
                        compressor=("none",), seed_zoo=False)
    slow = search_strategies(
        tr, spec, space,
        cost_model=CostModel(spec, link_profile={"ici_gbps": 0.05},
                             quant_profile={"int8_s_per_elem": 1e-12}))
    assert slow.winner is not None and "kern" in slow.winner.name
    fast = search_strategies(
        tr, spec, space,
        cost_model=CostModel(
            spec, link_profile={"ici_gbps": 1e5},
            quant_profile={"int8_s_per_elem": 1e-7},
            kernel_profile={"quant_ring_qdq_factor": 4.0}))
    assert fast.winner is not None and "kern" not in fast.winner.name
    # Both points were enumerated and priced in both runs.
    names = {c.name for c in slow.frontier}
    assert any("kern" in n for n in names) \
        and any("kern" not in n for n in names)


def test_search_matmul_kernel_election_flips_both_directions():
    """Regression: the fused collective-matmul proxy is one-sidedly
    better (a launch credit with no offsetting term), so dominance
    pruning inside one sibling group would delete the composed sibling
    before real pricing — and the election could never flip back to
    composed when calibration disfavors fusion.  Kernel points group
    separately (KnobConfig.mesh_key), so BOTH must reach pricing and
    the winner must follow the calibrated fused_hop_alpha_s."""
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.simulator.search import (SearchSpace,
                                               search_strategies)

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    tr.tokens_per_step = 64 * 512
    spec = ResourceSpec(TP_SPEC)
    space = SearchSpace(tp=(2,), num_microbatches=(2,),
                        vocab_parallel=(False,), zero_stage=(0,),
                        comm_overlap=("matmul",),
                        collective_precision=(None,),
                        compressor=("none",), seed_zoo=False)
    fused_wins = search_strategies(
        tr, spec, space,
        cost_model=CostModel(
            spec, link_profile={"hop_alpha_s": 1e-2},
            kernel_profile={"fused_hop_alpha_s": 1e-8}))
    assert fused_wins.winner is not None \
        and "kern" in fused_wins.winner.name
    composed_wins = search_strategies(
        tr, spec, space,
        cost_model=CostModel(
            spec, link_profile={"hop_alpha_s": 1e-8},
            kernel_profile={"fused_hop_alpha_s": 1e-2}))
    assert composed_wins.winner is not None \
        and "kern" not in composed_wins.winner.name, \
        composed_wins.winner.name
    names = {c.name for c in composed_wins.frontier}
    assert any("kern" in n for n in names) \
        and any("kern" not in n for n in names)


def test_flash_decode_election_pinned_both_directions():
    from autodist_tpu.simulator.cost_model import CostModel

    cfg = _lm_cfg(max_len=64)
    tr = _lm_trainable(cfg)
    spec = ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8}})
    cm = CostModel(spec, kernel_profile={
        "flash_decode_crossover_len": 1024,
        "flash_decode_speedup": 1.6,
        "flash_decode_short_penalty": 0.8})
    flash = {"tensor_parallel": 1, "kernel": ("flash_decode",)}
    einsum = {"tensor_parallel": 1}
    # Past the crossover: flash wins.
    long_f = cm.decode_cost(tr, flash, max_len=4096)
    long_e = cm.decode_cost(tr, einsum, max_len=4096)
    assert long_f.token_time_s < long_e.token_time_s
    assert long_f.kernel == ("flash_decode",)
    # Below it: the kernel's fixed overhead loses to plain einsum.
    short_f = cm.decode_cost(tr, flash, max_len=128)
    short_e = cm.decode_cost(tr, einsum, max_len=128)
    assert short_f.token_time_s > short_e.token_time_s


def test_rank_serving_orders_flash_by_crossover():
    from autodist_tpu.simulator import rank_serving

    cfg = _lm_cfg(max_len=64)
    tr = _lm_trainable(cfg)
    spec = ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8}})
    cands = [{"tensor_parallel": 1},
             {"tensor_parallel": 1, "kernel": ("flash_decode",)}]
    long = rank_serving(tr, spec, cands, max_len=4096)
    assert long[0][0].get("kernel") == ("flash_decode",)
    short = rank_serving(tr, spec, cands, max_len=128)
    assert short[0][0].get("kernel") is None


# --------------------------------------------------------------------------- #
# Serving engine: the attention_fn gate + flash decode parity
# --------------------------------------------------------------------------- #
def test_engine_rejects_foreign_attention_fn_naming_the_kernel():
    from autodist_tpu.serving import ServingEngine

    cfg = _lm_cfg()
    params = _lm_trainable(cfg).params
    bad = dataclasses.replace(cfg,
                              attention_fn=lambda q, k, v, m, r: q)
    with pytest.raises(NotImplementedError, match="flash"):
        ServingEngine(bad, params, num_slots=2)
    # A non-attention helper that happens to live in ops/
    # flash_attention.py (here: make_attention_fn itself, uncalled) is
    # NOT the flash family — it must get the same coded rejection, not
    # a trace-time shape error inside prefill.
    from autodist_tpu.ops import make_attention_fn
    oops = dataclasses.replace(cfg, attention_fn=make_attention_fn)
    with pytest.raises(NotImplementedError, match="flash"):
        ServingEngine(oops, params, num_slots=2)


def test_engine_flash_decode_greedy_parity_with_attention_fn():
    """The decode-parity gate: with the flash attention_fn accepted,
    greedy decode stays token-for-token against the sequential_logits
    reference (which runs the same attention_fn)."""
    from autodist_tpu.models.pipeline_lm import sequential_logits
    from autodist_tpu.ops import make_attention_fn
    from autodist_tpu.serving import ServingEngine

    base = _lm_cfg(vocab_size=33, max_len=24)
    params = _lm_trainable(base).params
    cfg = dataclasses.replace(base, attention_fn=make_attention_fn(
        causal=True, block_q=8, block_k=8))
    eng = ServingEngine(cfg, params, num_slots=2, max_len=24,
                        prefill_len=8, decode_steps=4)
    assert eng.kernel.get("flash_decode")
    r = np.random.RandomState(1)
    prompts = np.zeros((2, 8), np.int32)
    p_lens = np.array([5, 3], np.int32)
    prompts[0, :5] = r.randint(1, 33, 5)
    prompts[1, :3] = r.randint(1, 33, 3)
    toks = [eng.prefill(prompts, p_lens, np.array([True, True]))]
    for _ in range(2):
        toks.extend(list(eng.decode(np.array([True, True]))))
    gen = np.stack(toks)

    def ref_greedy(prompt, plen, steps):
        seq = list(prompt[:plen])
        out = []
        for _ in range(steps):
            logits = sequential_logits(cfg, params,
                                       jnp.asarray(seq)[None])
            nxt = int(jnp.argmax(logits[0, -1]))
            out.append(nxt)
            seq.append(nxt)
        return out

    for b in range(2):
        assert [int(t[b]) for t in gen] == ref_greedy(
            prompts[b], p_lens[b], len(gen))


def test_engine_seeds_kernel_from_strategy():
    from autodist_tpu.serving.engine import seed_engine_kwargs
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = _lm_cfg()
    tr = _lm_trainable(cfg)
    s = Pipeline(num_microbatches=2, tensor_parallel=2,
                 collective_precision={"tp_psum": "int8"},
                 kernel=("quant_ring", "flash_decode")).build(
        tr, ResourceSpec(TP_SPEC))
    kw = seed_engine_kwargs({}, s)
    assert kw["kernel"] == {"flash_decode": True, "quant_ring": True}


# --------------------------------------------------------------------------- #
# Telemetry: the kernel/<name>_elected schema gate
# --------------------------------------------------------------------------- #
def _write_run(tmp_path, gauges, run_annotations):
    import time as _time

    run = tmp_path / "run"
    run.mkdir(parents=True)
    with open(run / "metrics.jsonl", "w") as f:
        for name, value in gauges:
            f.write(json.dumps({"kind": "gauge", "name": name,
                                "value": value}) + "\n")
    with open(run / "manifest.json", "w") as f:
        json.dump({"kind": "manifest", "provenance": {},
                   "time": _time.time(), "run": run_annotations}, f)
    return str(run)


def test_telemetry_check_gates_kernel_gauge(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", "tools/telemetry_report.py")
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)

    ok = _write_run(tmp_path, [("kernel/quant_ring_elected", 1)],
                    {"kernel": ["quant_ring"]})
    assert tr.check_schema(ok) == []
    # Declared but never elected: the gauge is missing.
    missing = _write_run(tmp_path.joinpath("m"),
                         [], {"kernel": ["quant_ring"]})
    assert any("quant_ring" in p for p in tr.check_schema(missing))
    # A gauge naming an unregistered kernel fails.
    bogus = _write_run(tmp_path.joinpath("b"),
                       [("kernel/warp_drive_elected", 1)], {})
    assert any("unregistered" in p for p in tr.check_schema(bogus))


def test_pipeline_lowering_emits_kernel_gauge():
    from autodist_tpu import telemetry

    cfg = _lm_cfg()
    batch = _lm_batch(cfg.vocab_size)
    runner = AutoDist(TP_SPEC, "Pipeline", num_microbatches=2,
                      tensor_parallel=2,
                      collective_precision={"tp_psum": "int8"},
                      kernel=("quant_ring",)).build(_lm_trainable(cfg))
    try:
        gauge = telemetry.get().gauge("kernel/quant_ring_elected")
        assert gauge.value == 1
    finally:
        runner.close()
