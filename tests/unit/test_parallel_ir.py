"""Advanced parallelisms as first-class Strategy-IR citizens.

The reference's IR anticipated per-node distribution choices
(``strategy.proto:40-42``); these tests pin the promoted form: sequence /
pipeline / expert parallelism built as *serializable strategies* through
``AutoDist(spec, builder).build(trainable)``, with golden equality
against single-device execution and JSON round-trips.
"""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist, PipelineTrainable, Trainable
from autodist_tpu.parallel.moe import expert_parallel_ffn
from autodist_tpu.parallel.ring_attention import ring_self_attention
from autodist_tpu.parallel.sequence import global_positions
from autodist_tpu.strategy.ir import Strategy

pytestmark = pytest.mark.slow

VOCAB, DIM, HEADS, SEQ = 64, 32, 2, 32


# --------------------------------------------------------------------------- #
# Sequence parallelism through the IR
# --------------------------------------------------------------------------- #
class TinyCausalLM(nn.Module):
    attention: any
    positions: any

    @nn.compact
    def __call__(self, tokens):
        B, L = tokens.shape
        embed = nn.Embed(VOCAB, DIM, name="embed")
        pos_table = self.param("pos", nn.initializers.normal(0.02),
                               (SEQ, DIM))
        x = embed(tokens) + pos_table[self.positions(L)]
        qkv = nn.Dense(3 * DIM, name="qkv")(x).reshape(B, L, 3, HEADS,
                                                       DIM // HEADS)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = self.attention(q, k, v).reshape(B, L, DIM)
        x = x + nn.Dense(DIM, name="out")(o)
        x = nn.LayerNorm(name="ln")(x)
        return embed.attend(x)


def plain_causal_attention(q, k, v):
    depth = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(depth)
    L = q.shape[1]
    mask = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def make_lm_trainable(sharded: bool):
    if sharded:
        attn = lambda q, k, v: ring_self_attention(q, k, v, axis_name="seq",
                                                   causal=True)
        pos = lambda L: global_positions(L)
    else:
        attn = plain_causal_attention
        pos = lambda L: jnp.arange(L)
    model = TinyCausalLM(attention=attn, positions=pos)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["y"][..., None], axis=-1)
        return -jnp.mean(ll)

    init_model = TinyCausalLM(attention=plain_causal_attention,
                              positions=lambda L: jnp.arange(L))
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((2, SEQ), jnp.int32))["params"]
    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.5))


def lm_batches(n):
    r = np.random.RandomState(0)
    out = []
    for _ in range(n):
        x = r.randint(0, VOCAB, (8, SEQ)).astype(np.int32)
        out.append({"x": x, "y": np.roll(x, -1, axis=1)})
    return out


SEQ_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 2, "seq": 4}}


def test_sequence_parallel_through_autodist_matches_single_device():
    """The VERDICT round-3 'done' bar: a ring-attention sequence-parallel
    transformer trained end-to-end through
    ``AutoDist(spec, "SequenceParallel").build(trainable)`` reproduces
    the unsharded single-device run exactly."""
    ad = AutoDist(SEQ_SPEC, "SequenceParallel")
    trainable = make_lm_trainable(sharded=True)
    runner = ad.build(trainable)
    bs = lm_batches(3)
    for b in bs:
        metrics = runner.step(b, rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(metrics["loss"])))

    ref = make_lm_trainable(sharded=False)
    params = ref.params
    opt_state = ref.optimizer.init(params)
    for b in bs:
        def loss_for(p):
            l, _, _ = ref.loss(p, None, jax.tree.map(jnp.asarray, b),
                               jax.random.PRNGKey(0))
            return l
        grads = jax.grad(loss_for)(params)
        updates, opt_state = ref.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-5, atol=2e-5),
        runner.get_params(), jax.device_get(params))


def test_sequence_strategy_serializes_and_rebuilds():
    """The serialized strategy is a complete artifact: a worker
    deserializing the JSON (the chief→worker handoff) lowers to the same
    program and computes the same numbers."""
    ad = AutoDist(SEQ_SPEC, "SequenceParallel")
    trainable = make_lm_trainable(sharded=True)
    strategy = ad.build_or_load_strategy(trainable)
    assert strategy.graph_config.lowering == "sequence"
    assert strategy.graph_config.parallel == {"seq_leaves": ["x", "y"]}

    clone = Strategy.from_json(strategy.to_json())
    assert clone.graph_config.to_dict() == strategy.graph_config.to_dict()
    assert [n.to_dict() for n in clone.node_configs] \
        == [n.to_dict() for n in strategy.node_configs]

    b = lm_batches(1)[0]
    r1 = ad.build(trainable, strategy)
    m1 = r1.step(b, rng=jax.random.PRNGKey(0))
    r2 = ad.build(make_lm_trainable(sharded=True), clone)
    m2 = r2.step(b, rng=jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(np.asarray(m1["loss"])),
                               float(np.asarray(m2["loss"])), rtol=1e-6)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-6, atol=1e-6),
        r1.get_params(), r2.get_params())


def test_sequence_runner_checkpoint_roundtrip(tmp_path):
    """Saver works on the promoted lowering: exact resume."""
    from autodist_tpu.checkpoint.saver import Saver

    ad = AutoDist(SEQ_SPEC, "SequenceParallel")
    runner = ad.build(make_lm_trainable(sharded=True))
    bs = lm_batches(2)
    runner.step(bs[0], rng=jax.random.PRNGKey(0))
    saver = Saver(str(tmp_path))
    saver.save(runner)

    runner.step(bs[1], rng=jax.random.PRNGKey(1))
    stepped = jax.device_get(runner.get_params())
    saver.restore(runner)
    assert runner.step_count == 1
    runner.step(bs[1], rng=jax.random.PRNGKey(1))
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-6, atol=1e-6),
        jax.device_get(runner.get_params()), stepped)
    saver.close()


# --------------------------------------------------------------------------- #
# Pipeline parallelism through the IR
# --------------------------------------------------------------------------- #
S_STAGES, HID = 4, 8


def mlp_stage(params, x):
    return jax.nn.relu(x @ params["w"] + params["b"])


def mse_head(outputs, batch):
    l = jnp.mean((outputs - batch["y"]) ** 2)
    return l, {}


def make_pipeline_trainable(seed=0):
    r = np.random.RandomState(seed)
    stacked = {"w": jnp.asarray(r.randn(S_STAGES, HID, HID) * 0.5,
                                jnp.float32),
               "b": jnp.asarray(r.randn(S_STAGES, HID) * 0.1, jnp.float32)}
    return PipelineTrainable(mlp_stage, stacked, mse_head, optax.sgd(0.05),
                             num_stages=S_STAGES)


PIPE_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
             "mesh": {"data": 2, "pipe": 4}}


def pipe_batches(n, seed=2):
    r = np.random.RandomState(seed)
    return [{"x": r.randn(8, HID).astype(np.float32),
             "y": r.randn(8, HID).astype(np.float32)} for _ in range(n)]


def sequential_train(trainable, batches):
    """Single-device reference: PipelineTrainable.loss IS the sequential
    semantics."""
    params = trainable.params
    opt_state = trainable.optimizer.init(params)
    losses = []
    for b in batches:
        jb = jax.tree.map(jnp.asarray, b)

        def loss_for(p):
            l, _, _ = trainable.loss(p, None, jb, None)
            return l

        losses.append(float(loss_for(params)))
        g = jax.grad(loss_for)(params)
        upd, opt_state = trainable.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)
    return params, losses


def test_pipeline_through_autodist_matches_sequential():
    ad = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2)
    trainable = make_pipeline_trainable()
    runner = ad.build(trainable)
    bs = pipe_batches(3)
    losses = []
    for b in bs:
        m = runner.step(b)
        losses.append(float(np.asarray(m["loss"])))

    ref_params, ref_losses = sequential_train(make_pipeline_trainable(), bs)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), jax.device_get(ref_params))


def test_pipeline_strategy_serializes():
    ad = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2)
    strategy = ad.build_or_load_strategy(make_pipeline_trainable())
    assert strategy.graph_config.lowering == "pipeline"
    expected = {"num_microbatches": 2, "virtual_stages": 1,
                "remat": False, "tensor_parallel": 1,
                "comm_overlap": None, "vocab_parallel": False,
                "zero_stage": 0}
    assert strategy.graph_config.parallel == expected
    clone = Strategy.from_json(strategy.to_json())
    assert clone.graph_config.parallel == expected
    # every stage variable is pipe-sharded in the IR
    for n in clone.node_configs:
        assert n.partitioner.spec[0] == "pipe"


def test_pipeline_composes_with_grad_accumulation():
    """GraphConfig.accum_steps x pipeline microbatching: each accumulation
    slice runs the full schedule; the update equals one big-batch
    sequential step (linear-in-loss grads: mean of slice grads == full
    grad only when slices are equal-sized, which they are)."""
    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import Pipeline

    ad = AutoDist(PIPE_SPEC,
                  GradAccumulation(Pipeline(num_microbatches=2), steps=2))
    trainable = make_pipeline_trainable()
    runner = ad.build(trainable)
    b = pipe_batches(1, seed=5)[0]  # [8, HID] -> 2 accum slices of 4
    runner.step(b)

    ref_params, _ = sequential_train(make_pipeline_trainable(), [b])
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), jax.device_get(ref_params))


# --------------------------------------------------------------------------- #
# Expert parallelism through the IR
# --------------------------------------------------------------------------- #
E, M_DIM, H_DIM, G = 8, 16, 32, 8   # 8 experts over 4 devices, G tokens/dev

EXPERT_SPEC = {"topology": {"platform": "cpu", "num_devices": 4},
               "mesh": {"expert": 4}}


def make_moe_trainable(seed=0):
    r = np.random.RandomState(seed)
    params = {
        "gate": jnp.asarray(r.randn(M_DIM, E) * 0.5, jnp.float32),
        "moe_wi": jnp.asarray(r.randn(E, M_DIM, H_DIM) * 0.2, jnp.float32),
        "moe_wo": jnp.asarray(r.randn(E, H_DIM, M_DIM) * 0.2, jnp.float32),
    }

    def loss_fn(p, batch):
        out, aux = expert_parallel_ffn(batch["x"], p["gate"], p["moe_wi"],
                                       p["moe_wo"], capacity_factor=4.0)
        return jnp.mean((out - batch["y"]) ** 2) + 0.01 * aux

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-2))


def test_expert_parallel_through_autodist_trains():
    ad = AutoDist(EXPERT_SPEC, "ExpertParallel")
    trainable = make_moe_trainable()
    runner = ad.build(trainable)

    # expert tables are stored sharded on the expert axis
    spec_wi = runner.lowered.state_specs["params"]["moe_wi"]
    assert spec_wi == P("expert", None, None)
    assert runner.lowered.state_specs["params"]["gate"] == P()

    r = np.random.RandomState(3)
    x = r.randn(4 * G, M_DIM).astype(np.float32)
    y = (x @ (r.randn(M_DIM, M_DIM).astype(np.float32) * 0.1))
    losses = []
    for _ in range(10):
        m = runner.step({"x": x, "y": y})
        losses.append(float(np.asarray(m["loss"])))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_expert_strategy_serializes_and_marks_experts():
    ad = AutoDist(EXPERT_SPEC, "ExpertParallel")
    strategy = ad.build_or_load_strategy(make_moe_trainable())
    assert strategy.graph_config.lowering == "expert"
    by_name = {n.var_name: n for n in strategy.node_configs}
    assert by_name["moe_wi"].partitioner.spec[0] == "expert"
    assert by_name["moe_wo"].partitioner.spec[0] == "expert"
    assert by_name["gate"].partitioner is None
    clone = Strategy.from_json(strategy.to_json())
    assert {n.var_name: bool(n.partitioner) for n in clone.node_configs} \
        == {n.var_name: bool(n.partitioner) for n in strategy.node_configs}


def test_expert_parallel_requires_expert_vars():
    ad = AutoDist(EXPERT_SPEC, "ExpertParallel")
    plain = Trainable.from_loss_fn(
        lambda p, b: jnp.sum(p["w"] * b["x"]),
        {"w": jnp.ones((4, 4))}, optax.sgd(0.1))
    with pytest.raises(ValueError, match="no expert variables"):
        ad.build_or_load_strategy(plain)


# --------------------------------------------------------------------------- #
# MoE transformer LM model family through ExpertParallel
# --------------------------------------------------------------------------- #
def test_moe_transformer_lm_trains_expert_parallel():
    """The bundled MoE LM model family trains through the ExpertParallel
    strategy: expert tables sharded, gate replicated (never auto-sharded
    despite its 'moe'-scoped name), loss decreasing, aux loss finite."""
    import optax

    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)

    cfg = MoeConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, expert_hidden=32, num_experts=4,
                    max_len=32, dtype=jnp.float32)
    trainable = make_moe_lm_trainable(cfg, optax.adam(1e-2),
                                      jax.random.PRNGKey(0),
                                      batch_size=4, seq_len=16)
    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                   "mesh": {"expert": 4}}, "ExpertParallel")
    strategy = ad.build_or_load_strategy(trainable)
    by_name = {n.var_name: n for n in strategy.node_configs}
    assert by_name["layer_0_moe/expert_wi"].partitioner is not None
    assert by_name["layer_0_moe/expert_wo"].partitioner is not None
    assert by_name["layer_0_moe/expert_gate"].partitioner is None

    runner = ad.build(trainable, strategy)
    r = np.random.RandomState(0)
    x = r.randint(0, 64, (8, 16)).astype(np.int32)
    batch = {"x": x, "y": np.roll(x, -1, axis=1)}
    losses = []
    for _ in range(8):
        m = runner.step(batch)
        losses.append(float(np.asarray(m["loss"])))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert np.isfinite(float(np.asarray(m["aux"])))


def test_expert_parallel_sgd_matches_dense_golden():
    """With ample capacity (no token drops) the per-token MoE output is
    independent of routing-group composition, so expert-parallel SGD must
    reproduce the dense single-device run EXACTLY — including gradient
    scale on the expert tables (a missing 1/E_shards would train experts
    at an E-scaled learning rate; adam's scale invariance hides it, sgd
    does not).  aux_weight-free loss: the balance term is group-local by
    construction."""
    import optax

    E_, M_, H_, G_ = 4, 8, 16, 8

    def make(seed=0):
        r = np.random.RandomState(seed)
        params = {
            "gate": jnp.asarray(r.randn(M_, E_) * 0.5, jnp.float32),
            "moe_wi": jnp.asarray(r.randn(E_, M_, H_) * 0.2, jnp.float32),
            "moe_wo": jnp.asarray(r.randn(E_, H_, M_) * 0.2, jnp.float32),
        }

        def loss_fn(p, batch):
            out, _ = expert_parallel_ffn(batch["x"], p["gate"],
                                         p["moe_wi"], p["moe_wo"],
                                         capacity_factor=float(E_))
            return jnp.mean((out - batch["y"]) ** 2)

        return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))

    r = np.random.RandomState(1)
    x = r.randn(4 * G_, M_).astype(np.float32)
    y = (x @ (r.randn(M_, M_).astype(np.float32) * 0.1))
    batch = {"x": x, "y": y}

    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                   "mesh": {"expert": 4}}, "ExpertParallel")
    runner = ad.build(make())
    for _ in range(3):
        runner.step(batch)

    # dense single-device reference: same loss fn on a 1-device expert
    # mesh is just dense routing of all tokens at once — but the group
    # partition differs, so instead run the sharded semantics by hand:
    # mean over the 4 groups of each group's local-mean loss.
    ref = make()
    params = ref.params
    opt_state = ref.optimizer.init(params)
    from autodist_tpu.parallel.moe import dense_moe_reference
    capacity = max(int(np.ceil(2 * G_ * float(E_) / E_)), 4)

    def group_loss(p, xb, yb):
        out, _ = dense_moe_reference(xb, p["gate"], p["moe_wi"],
                                     p["moe_wo"], capacity)
        return jnp.mean((out - yb) ** 2)

    def total_loss(p):
        losses = [group_loss(p, jnp.asarray(x[g * G_:(g + 1) * G_]),
                             jnp.asarray(y[g * G_:(g + 1) * G_]))
                  for g in range(4)]
        return sum(losses) / 4.0

    for _ in range(3):
        g = jax.grad(total_loss)(params)
        upd, opt_state = ref.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)

    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), jax.device_get(params))


# --------------------------------------------------------------------------- #
# Pipelined transformer LM (shared embedding + stage ring)
# --------------------------------------------------------------------------- #
def make_plm(seed=0, num_stages=4):
    import optax

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=4,
                            num_heads=2, mlp_dim=64, max_len=32,
                            dropout_rate=0.0, attention_dropout_rate=0.0,
                            dtype=jnp.float32, causal=True)
    return make_pipeline_lm_trainable(cfg, optax.sgd(0.1),
                                      jax.random.PRNGKey(seed),
                                      num_stages=num_stages)


def plm_batch(seed=1):
    r = np.random.RandomState(seed)
    x = r.randint(0, 64, (8, 16)).astype(np.int32)
    return {"x": x, "y": np.roll(x, -1, axis=1)}


def test_pipelined_lm_matches_sequential():
    """A real transformer LM through AutoDist(spec, Pipeline): shared
    embedding/unembedding params (prologue + head) and the stage ring
    reproduce the sequential PipelineTrainable.loss exactly over
    training steps."""
    import optax

    from autodist_tpu.strategy.parallel_builders import Pipeline

    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                   "mesh": {"pipe": 4}}, Pipeline(num_microbatches=2))
    trainable = make_plm()
    runner = ad.build(trainable)
    b = plm_batch()
    losses = []
    for _ in range(3):
        m = runner.step(b)
        losses.append(float(np.asarray(m["loss"])))

    ref = make_plm()
    params = ref.params
    opt_state = ref.optimizer.init(params)
    ref_losses = []
    for _ in range(3):
        def loss_for(p):
            l, _, _ = ref.loss(p, None, jax.tree.map(jnp.asarray, b), None)
            return l
        ref_losses.append(float(loss_for(params)))
        g = jax.grad(loss_for)(params)
        upd, opt_state = ref.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    got = runner.get_params()
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4),
        got, jax.device_get(params))


def test_pipelined_lm_interleaved_virtual_stages():
    """The same LM with 4 layers over 2 devices x 2 virtual stages."""
    from autodist_tpu.strategy.parallel_builders import Pipeline

    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 2},
                   "mesh": {"pipe": 2}},
                  Pipeline(num_microbatches=2, virtual_stages=2))
    runner = ad.build(make_plm())
    b = plm_batch()
    m0 = runner.step(b)
    l0 = float(np.asarray(m0["loss"]))
    for _ in range(4):
        m = runner.step(b)
    assert float(np.asarray(m["loss"])) < l0
    assert np.isfinite(float(np.asarray(m["accuracy"])))


def test_pipelined_lm_accepts_dropout_config():
    """Round-4 Missing #6 closed: a regularized pipelined LM builds with
    stage_rng threading (equivalence goldens live in
    test_pipeline_dropout.py)."""
    import optax

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=4,
                            num_heads=2, mlp_dim=64, max_len=32,
                            dropout_rate=0.1, causal=True)
    t = make_pipeline_lm_trainable(cfg, optax.sgd(0.1),
                                   jax.random.PRNGKey(0))
    assert t.stage_rng


def test_pipeline_shared_leaf_with_stagecount_dim_stays_replicated():
    """A shared leaf whose leading dim equals the chunk count must not
    get pipe-sharded optimizer state (the 'leading dim == C' heuristic
    is stages-only)."""
    import optax

    from autodist_tpu.parallel.pipeline import _build_pipeline

    n, HID_ = 4, 8
    mesh = jax.make_mesh((n,), ("pipe",))
    r = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r.randn(n, HID_, HID_) * 0.3, jnp.float32)}
    shared = {"scale4": jnp.ones((n,), jnp.float32)}  # dim == C == 4!

    def stage(p, x):
        return jax.nn.relu(x @ p["w"])

    def prologue(sh, batch):
        return batch["x"] * sh["scale4"].sum() / n

    def head(out, batch, sh):
        return jnp.mean((out - batch["y"]) ** 2), {}

    built = _build_pipeline(stage, stacked, head, optax.adam(1e-2), mesh,
                            num_microbatches=2, shared_params=shared,
                            prologue=prologue)
    state = built.init_fn({"stages": stacked, "shared": shared})
    b = {"x": r.randn(8, HID_).astype(np.float32),
         "y": r.randn(8, HID_).astype(np.float32)}
    state, m = built.step_fn(state, jax.tree.map(jnp.asarray, b),
                             jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_pipelined_lm_grad_accum_matches_big_batch():
    """accum_steps composes with shared params: two accumulated slices
    equal one big sequential batch (linear loss-mean grads)."""
    import optax

    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import Pipeline

    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                   "mesh": {"pipe": 4}},
                  GradAccumulation(Pipeline(num_microbatches=2), steps=2))
    trainable = make_plm()
    runner = ad.build(trainable)
    b = plm_batch(seed=9)
    runner.step(b)

    ref = make_plm()
    params = ref.params
    opt_state = ref.optimizer.init(params)

    def loss_for(p):
        l, _, _ = ref.loss(p, None, jax.tree.map(jnp.asarray, b), None)
        return l

    g = jax.grad(loss_for)(params)
    upd, opt_state = ref.optimizer.update(g, opt_state, params)
    expect = optax.apply_updates(params, upd)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4),
        runner.get_params(), jax.device_get(expect))


def test_sequence_parallel_grad_accum_matches_big_batch():
    """GradAccumulation(SequenceParallel): two accumulated slices equal
    one big batch (the sequence lowering honors accum_steps)."""
    import optax

    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import SequenceParallel

    ad = AutoDist(SEQ_SPEC,
                  GradAccumulation(SequenceParallel(), steps=2))
    trainable = make_lm_trainable(sharded=True)
    runner = ad.build(trainable)
    b = lm_batches(1)[0]
    runner.step(b, rng=jax.random.PRNGKey(0))

    ref = make_lm_trainable(sharded=False)
    params = ref.params
    opt_state = ref.optimizer.init(params)

    def loss_for(p):
        l, _, _ = ref.loss(p, None, jax.tree.map(jnp.asarray, b),
                           jax.random.PRNGKey(0))
        return l

    g = jax.grad(loss_for)(params)
    upd, opt_state = ref.optimizer.update(g, opt_state, params)
    expect = optax.apply_updates(params, upd)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-5, atol=2e-5),
        runner.get_params(), jax.device_get(expect))


def test_pipeline_portable_checkpoint_runs_sequentially(tmp_path):
    """The 'checkpoints look unpartitioned' contract for pipelines: a
    portable save restores as plain host arrays in logical stage order,
    and sequential single-device execution of those params reproduces
    the pipelined runner's eval loss exactly."""
    from autodist_tpu.checkpoint.saver import Saver
    from autodist_tpu.strategy.parallel_builders import Pipeline

    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 4},
                   "mesh": {"pipe": 4}}, Pipeline(num_microbatches=2))
    trainable = make_plm()
    runner = ad.build(trainable)
    b = plm_batch(seed=4)
    runner.step(b)
    pipe_eval = float(np.asarray(runner.eval_step(b)["loss"]))

    saver = Saver(str(tmp_path))
    saver.save(runner, portable=True)
    payload = saver.restore_params()
    saver.close()

    params = jax.tree.map(jnp.asarray, payload["params"])
    seq_loss, _, _ = trainable.loss(params, None,
                                    jax.tree.map(jnp.asarray, b), None)
    np.testing.assert_allclose(pipe_eval, float(seq_loss),
                               rtol=1e-5, atol=1e-6)
