"""ZeRO-1 / compressor composition with the parallel lowerings.

The reference's defining trick was *per-variable heterogeneous* sync
(``parallax_strategy.py:24-71``); round-4's parallel lowerings replicated
every parameter's optimizer state and ignored synchronizer configs.
These tests pin the composition: a ``PSSynchronizer`` node config under
the sequence/expert/pipeline lowerings shards the optimizer state
(ZeRO-1) while reproducing the replicated run golden-exactly, and
``AllReduceSynchronizer(compressor=...)`` configs run the compressed
allreduce (bf16 wire ≙ lossless for these magnitudes; EF state rows
persist per device).
"""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist, Trainable
from autodist_tpu.parallel.ring_attention import ring_self_attention
from autodist_tpu.parallel.sequence import global_positions

pytestmark = pytest.mark.slow

VOCAB, DIM, HEADS, SEQ = 64, 32, 2, 32


class TinyCausalLM(nn.Module):
    attention: any
    positions: any

    @nn.compact
    def __call__(self, tokens):
        B, L = tokens.shape
        embed = nn.Embed(VOCAB, DIM, name="embed")
        pos_table = self.param("pos", nn.initializers.normal(0.02),
                               (SEQ, DIM))
        x = embed(tokens) + pos_table[self.positions(L)]
        qkv = nn.Dense(3 * DIM, name="qkv")(x).reshape(B, L, 3, HEADS,
                                                       DIM // HEADS)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = self.attention(q, k, v).reshape(B, L, DIM)
        x = x + nn.Dense(DIM, name="out")(o)
        x = nn.LayerNorm(name="ln")(x)
        return embed.attend(x)


def plain_causal_attention(q, k, v):
    depth = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(depth)
    L = q.shape[1]
    mask = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def make_lm_trainable(sharded: bool, opt=None):
    if sharded:
        attn = lambda q, k, v: ring_self_attention(q, k, v, axis_name="seq",
                                                   causal=True)
        pos = lambda L: global_positions(L)
    else:
        attn = plain_causal_attention
        pos = lambda L: jnp.arange(L)
    model = TinyCausalLM(attention=attn, positions=pos)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["y"][..., None], axis=-1)
        return -jnp.mean(ll)

    init_model = TinyCausalLM(attention=plain_causal_attention,
                              positions=lambda L: jnp.arange(L))
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((2, SEQ), jnp.int32))["params"]
    return Trainable.from_loss_fn(loss_fn, params,
                                  opt or optax.adam(1e-2))


def lm_batches(n):
    r = np.random.RandomState(0)
    out = []
    for _ in range(n):
        x = r.randint(0, VOCAB, (8, SEQ)).astype(np.int32)
        out.append({"x": x, "y": np.roll(x, -1, axis=1)})
    return out


def reference_train(trainable, batches):
    params = trainable.params
    opt_state = trainable.optimizer.init(params)
    for b in batches:
        def loss_for(p):
            l, _, _ = trainable.loss(p, None, jax.tree.map(jnp.asarray, b),
                                     jax.random.PRNGKey(0))
            return l
        grads = jax.grad(loss_for)(params)
        updates, opt_state = trainable.optimizer.update(grads, opt_state,
                                                        params)
        params = optax.apply_updates(params, updates)
    return jax.device_get(params)


SEQ_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 2, "seq": 4}}


def assert_trees_close(a, b, rtol=2e-5, atol=2e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


def test_sequence_zero1_matches_replicated_run_and_shards_state():
    """VERDICT round-4 'done' bar: a sequence-parallel model with ZeRO-1
    optimizer state matches its replicated run golden-exactly — with
    Adam, so the sharded moments are load-bearing.  (The replicated
    sequence run itself is pinned against single-device execution in
    ``test_parallel_ir``; ZeRO only reorders the same sum/8 reduction,
    so the comparison is tight.)"""
    ad = AutoDist(SEQ_SPEC, "SequenceParallel", zero1=True)
    trainable = make_lm_trainable(sharded=True)
    runner = ad.build(trainable)
    bs = lm_batches(3)
    for b in bs:
        runner.step(b, rng=jax.random.PRNGKey(0))

    ad_rep = AutoDist(SEQ_SPEC, "SequenceParallel")
    rep_runner = ad_rep.build(make_lm_trainable(sharded=True))
    for b in bs:
        rep_runner.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(runner.get_params(), rep_runner.get_params(),
                       rtol=1e-5, atol=1e-6)

    # Sanity vs single-device (adam amplifies reduction-order fp noise;
    # loose bound only — the tight golden is the replicated run above).
    expected = reference_train(make_lm_trainable(sharded=False), bs)
    assert_trees_close(runner.get_params(), expected, rtol=5e-2,
                       atol=2e-3)

    # The optimizer moments are genuinely sharded: every adam moment leaf
    # is flat, padded, and partitioned over (data x seq) = all 8 devices.
    state = runner.state
    mu = state["opt_state"][0].mu
    flat_mu = jax.tree.leaves(mu)
    assert flat_mu, "adam state not found"
    for leaf in flat_mu:
        assert leaf.ndim == 1, "ZeRO-1 moment should be flat"
        spec = leaf.sharding.spec
        assert spec == P(("data", "seq")), spec
        assert leaf.shape[0] % 8 == 0, "flat shard must pad to 8 devices"


def test_sequence_zero1_strategy_serializes():
    """The PS node configs survive the JSON round-trip (chief→worker
    handoff carries the ZeRO choice)."""
    from autodist_tpu.strategy.ir import PSSynchronizer, Strategy

    ad = AutoDist(SEQ_SPEC, "SequenceParallel", zero1=True)
    trainable = make_lm_trainable(sharded=True)
    strategy = ad.build_or_load_strategy(trainable)
    clone = Strategy.from_json(strategy.to_json())
    assert all(isinstance(n.synchronizer, PSSynchronizer)
               for n in clone.node_configs)
    runner = ad.build(make_lm_trainable(sharded=True), clone)
    b = lm_batches(1)[0]
    m = runner.step(b, rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_sequence_compressor_bf16_ef_runs_and_stays_close():
    """Compressed allreduce under the sequence lowering: bf16+EF wire.
    Error feedback keeps the trajectory near the exact one; sync_state
    rows persist one per device."""
    ad = AutoDist(SEQ_SPEC, "SequenceParallel", compressor="bf16_ef")
    trainable = make_lm_trainable(sharded=True, opt=optax.sgd(0.1))
    runner = ad.build(trainable)
    bs = lm_batches(3)
    for b in bs:
        runner.step(b, rng=jax.random.PRNGKey(0))

    # EF residual state exists, one row per device.
    sync = runner.state["sync_state"]
    assert sync, "stateful compressor must persist sync_state"
    for row in jax.tree.leaves(sync):
        assert row.shape[0] == 8

    expected = reference_train(
        make_lm_trainable(sharded=False, opt=optax.sgd(0.1)), bs)
    assert_trees_close(runner.get_params(), expected, rtol=5e-2, atol=5e-3)


EXPERT_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
               "mesh": {"data": 2, "expert": 4}}


def make_moe_trainable(opt=None):
    from autodist_tpu.parallel.moe import (dense_moe_reference,
                                           expert_parallel_ffn)

    E, M, H, G = 4, 8, 16, 16
    r = np.random.RandomState(1)
    params = {
        "moe": {
            "gate": jnp.asarray(r.randn(M, E) * 0.1, jnp.float32),
            "expert_wi": jnp.asarray(r.randn(E, M, H) * 0.2, jnp.float32),
            "expert_wo": jnp.asarray(r.randn(E, H, M) * 0.2, jnp.float32),
        },
        "head": jnp.asarray(r.randn(M, 1) * 0.1, jnp.float32),
    }

    def loss_fn(p, batch):
        out, aux = expert_parallel_ffn(
            batch["x"], p["moe"]["gate"], p["moe"]["expert_wi"],
            p["moe"]["expert_wo"], capacity_factor=4.0)
        pred = out @ p["head"]
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2) + 0.01 * aux

    t = Trainable.from_loss_fn(loss_fn, params, opt or optax.adam(1e-2))
    return t


def moe_batches(n):
    r = np.random.RandomState(2)
    return [{"x": r.randn(64, 8).astype(np.float32),
             "y": r.randn(64).astype(np.float32)} for _ in range(n)]


def test_expert_zero1_shards_replicated_state_only():
    """ZeRO-1 under expert parallelism: replicated variables (gate, head)
    get flat (data x expert)-sharded moments; expert tables keep their
    expert-axis sharding (the PS request degrades with a warning)."""
    ad = AutoDist(EXPERT_SPEC, "ExpertParallel", zero1=True)
    trainable = make_moe_trainable()
    runner = ad.build(trainable)
    for b in moe_batches(3):
        m = runner.step(b, rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))

    mu = runner.state["opt_state"][0].mu
    gate_mu = mu["moe"]["gate"]
    assert gate_mu.ndim == 1 and gate_mu.sharding.spec == P(("data",
                                                             "expert"))
    head_mu = mu["head"]
    assert head_mu.ndim == 1
    # expert tables keep the parameter's expert-axis sharding
    wi_mu = mu["moe"]["expert_wi"]
    assert wi_mu.ndim == 3 and wi_mu.sharding.spec == P("expert")


# --------------------------------------------------------------------------- #
# Pipeline + ZeRO / compressor composition
# --------------------------------------------------------------------------- #
from autodist_tpu import PipelineTrainable

S_STAGES, HID = 4, 8
PIPE_SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
             "mesh": {"data": 2, "pipe": 4}}


def mlp_stage(params, x):
    return jax.nn.relu(x @ params["w"] + params["b"])


def mse_head(outputs, batch):
    return jnp.mean((outputs - batch["y"]) ** 2), {}


def make_pipeline_trainable(opt=None):
    r = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r.randn(S_STAGES, HID, HID) * 0.5,
                                jnp.float32),
               "b": jnp.asarray(r.randn(S_STAGES, HID) * 0.1, jnp.float32)}
    return PipelineTrainable(mlp_stage, stacked, mse_head,
                             opt or optax.adam(1e-2),
                             num_stages=S_STAGES)


def pipe_batches(n, seed=2):
    r = np.random.RandomState(seed)
    return [{"x": r.randn(8, HID).astype(np.float32),
             "y": r.randn(8, HID).astype(np.float32)} for _ in range(n)]


def test_pipeline_zero1_matches_plain_pipeline_and_shards_state():
    """VERDICT round-4 'done' bar: a pipelined LM trains with
    data-axis-sharded Adam moments, matching the replicated pipeline run
    golden-exactly."""
    ad0 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2)
    r0 = ad0.build(make_pipeline_trainable())
    ad1 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2, zero1=True)
    r1 = ad1.build(make_pipeline_trainable())
    bs = pipe_batches(3)
    for b in bs:
        m0 = r0.step(b)
        m1 = r1.step(b)
        np.testing.assert_allclose(float(np.asarray(m0["loss"])),
                                   float(np.asarray(m1["loss"])),
                                   rtol=1e-5)
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)

    # adam moments for stage vars: flat, sharded over (pipe x data)
    mu = r1.state["opt_state"][0].mu
    for leaf in jax.tree.leaves(mu):
        assert leaf.ndim == 1
        assert leaf.sharding.spec == P(("pipe", "data")), \
            leaf.sharding.spec
        assert leaf.shape[0] % 8 == 0


def test_pipeline_shared_params_zero1():
    """Shared (embedding/unembedding) variables ZeRO over pipe x data
    jointly; the pipelined transformer LM with shared groups still
    matches its replicated pipeline run."""
    VOCAB, D = 32, 8

    def stage(params, x):
        return x + jnp.tanh(x @ params["w"])

    def prologue(shared, batch):
        return shared["embed"][batch["x"]]

    def head(outputs, batch, shared):
        logits = outputs @ shared["embed"].T
        lp = jax.nn.log_softmax(logits, -1)
        ll = jnp.take_along_axis(lp, batch["y"][..., None], -1)
        return -jnp.mean(ll), {}

    def make(opt=None):
        r = np.random.RandomState(1)
        stacked = {"w": jnp.asarray(r.randn(S_STAGES, D, D) * 0.3,
                                    jnp.float32)}
        shared = {"embed": jnp.asarray(r.randn(VOCAB, D) * 0.1,
                                       jnp.float32)}
        return PipelineTrainable(stage, stacked, head,
                                 opt or optax.adam(1e-2),
                                 num_stages=S_STAGES,
                                 shared_params=shared, prologue=prologue)

    r = np.random.RandomState(4)
    bs = [{"x": r.randint(0, VOCAB, (8, 6)).astype(np.int32),
           "y": r.randint(0, VOCAB, (8, 6)).astype(np.int32)}
          for _ in range(3)]

    r0 = AutoDist(PIPE_SPEC, "Pipeline",
                  num_microbatches=2).build(make())
    r1 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2,
                  zero1=True).build(make())
    for b in bs:
        r0.step(b)
        r1.step(b)
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)
    mu = r1.state["opt_state"][0].mu
    assert mu["shared"]["embed"].ndim == 1
    assert mu["shared"]["embed"].sharding.spec == P(("pipe", "data"))
    assert mu["stages"]["w"].sharding.spec == P(("pipe", "data"))


def test_pipeline_compressor_runs_close_to_uncompressed():
    """bf16_ef compression over the data axis composes with the
    pipeline schedule; EF rows persist one per device."""
    r0 = AutoDist(PIPE_SPEC, "Pipeline",
                  num_microbatches=2).build(
                      make_pipeline_trainable(optax.sgd(0.05)))
    r1 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2,
                  compressor="bf16_ef").build(
                      make_pipeline_trainable(optax.sgd(0.05)))
    bs = pipe_batches(3)
    for b in bs:
        r0.step(b)
        r1.step(b)
    sync = r1.state["sync_state"]
    assert sync, "stateful compressor must persist sync_state"
    for row in jax.tree.leaves(sync):
        assert row.shape[0] == 8
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=5e-2,
                       atol=5e-3)


def test_pipeline_zero1_with_virtual_stages():
    """ZeRO composes with Megatron interleaving (V>1): the u-space
    layout groups each device's V chunks pipe-major."""
    def make(V_stages):
        r = np.random.RandomState(0)
        stacked = {"w": jnp.asarray(r.randn(8, HID, HID) * 0.3,
                                    jnp.float32),
                   "b": jnp.asarray(r.randn(8, HID) * 0.1, jnp.float32)}
        return PipelineTrainable(mlp_stage, stacked, mse_head,
                                 optax.adam(1e-2), num_stages=8)

    r0 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=4,
                  virtual_stages=2).build(make(2))
    r1 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=4,
                  virtual_stages=2, zero1=True).build(make(2))
    bs = pipe_batches(2)
    for b in bs:
        r0.step(b)
        r1.step(b)
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)


# --------------------------------------------------------------------------- #
# GSPMD ZeRO-1 (PS node configs honored by the gspmd lowering)
# --------------------------------------------------------------------------- #
def test_gspmd_zero1_shards_opt_state_and_matches():
    """TensorParallel(zero1=True): opt-state leading dims shard over the
    data axis (XLA derives the collectives); numerics match the
    non-zero TP run."""
    from autodist_tpu import models

    cfg = models.TransformerConfig(
        vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        attention_dropout_rate=0.0)
    spec = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 2, "model": 4}}
    model = models.TransformerLM(cfg)
    params0 = model.init({"params": jax.random.PRNGKey(0)},
                         jnp.zeros((2, 16), jnp.int32))["params"]

    def make():
        def loss_fn(p, batch):
            logits = model.apply({"params": p}, batch["x"],
                                 deterministic=True)
            lp = jax.nn.log_softmax(logits, -1)
            return -jnp.mean(jnp.take_along_axis(
                lp, batch["y"][..., None], -1))

        return Trainable.from_loss_fn(loss_fn, params0, optax.adam(1e-2))

    r = np.random.RandomState(0)
    bs = [{"x": r.randint(0, 64, (8, 16)).astype(np.int32),
           "y": r.randint(0, 64, (8, 16)).astype(np.int32)}
          for _ in range(2)]

    r0 = AutoDist(spec, "TensorParallel").build(make())
    r1 = AutoDist(spec, "TensorParallel", zero1=True).build(make())
    for b in bs:
        r0.step(b)
        r1.step(b)
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)

    # A replicated variable's moment: dim 0 sharded over data under zero1.
    mu0 = r0.state["opt_state"][0].mu
    mu1 = r1.state["opt_state"][0].mu
    ln = "ln_final"
    assert mu0[ln]["scale"].sharding.spec in (P(), P(None))
    assert mu1[ln]["scale"].sharding.spec == P("data")
    # A TP-sharded variable's moment keeps model sharding + gains data
    # on dim 0 when divisible.
    wo = mu1["encoder"]["layer_0"]["mlp"]["wo"]["kernel"]
    assert wo.sharding.spec == P(("model", "data"), None), wo.sharding.spec


# --------------------------------------------------------------------------- #
# Pipeline remat (Pipeline(remat=True))
# --------------------------------------------------------------------------- #
def test_pipeline_remat_matches_plain_numerics():
    """jax.checkpoint around the chunks changes memory, not math: the
    remat pipeline reproduces the plain pipeline exactly."""
    r0 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2).build(
        make_pipeline_trainable())
    r1 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2,
                  remat=True).build(make_pipeline_trainable())
    bs = pipe_batches(3)
    for b in bs:
        r0.step(b)
        r1.step(b)
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=1e-6,
                       atol=1e-7)


def test_cost_model_remat_rescues_infeasible_pipeline():
    """VERDICT round-4 'done' bar: an infeasible-without-remat case
    ranks Pipeline(remat=True) feasible (the activation envelope is
    priced; remat shrinks it to boundary activations)."""
    from autodist_tpu import PipelineTrainable
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t = make_pipeline_trainable()
    # Enormous per-token activation footprint vs tiny chip HBM: the
    # plain pipeline's act_hint*tokens/S term blows the budget; remat's
    # boundary-only term fits.
    t.tokens_per_step = 1 << 16
    t.act_bytes_per_token = 4e6
    rs = ResourceSpec(PIPE_SPEC)
    cm = CostModel(rs)
    plain = cm.strategy_cost(t, Pipeline(num_microbatches=2).build(t, rs))
    remat = cm.strategy_cost(
        t, Pipeline(num_microbatches=2, remat=True).build(t, rs))
    assert not plain.feasible
    assert remat.feasible
    assert remat.mem_bytes_per_device < plain.mem_bytes_per_device


# --------------------------------------------------------------------------- #
# Heterogeneous mixes + SSP + stateful-ring compressors under parallel
# lowerings
# --------------------------------------------------------------------------- #
def test_sequence_zero_min_bytes_mixes_per_variable():
    """Parallax-style heterogeneity through one knob: big variables get
    ZeRO-1 (flat sharded moments), small ones the compressed allreduce —
    per-variable node configs in the serialized strategy, both honored
    by the lowering."""
    from autodist_tpu.strategy.ir import (AllReduceSynchronizer,
                                          PSSynchronizer)

    trainable = make_lm_trainable(sharded=True)
    # every variable sits below a 16KB threshold -> uniform compressed AR
    ad = AutoDist(SEQ_SPEC, "SequenceParallel",
                  zero_min_bytes=16 * 1024, compressor="bf16")
    strategy = ad.build_or_load_strategy(trainable)
    by_name = {n.var_name: n for n in strategy.node_configs}
    assert all(isinstance(n.synchronizer, AllReduceSynchronizer)
               for n in by_name.values())
    # 5KB threshold splits: embed [64x32] f32 = 8KB -> PS; small -> AR
    ad2 = AutoDist(SEQ_SPEC, "SequenceParallel",
                   zero_min_bytes=5 * 1024, compressor="bf16")
    strategy2 = ad2.build_or_load_strategy(trainable)
    by_name2 = {n.var_name: n for n in strategy2.node_configs}
    assert isinstance(by_name2["embed/embedding"].synchronizer,
                      PSSynchronizer)
    assert isinstance(by_name2["ln/scale"].synchronizer,
                      AllReduceSynchronizer)
    assert by_name2["ln/scale"].synchronizer.compressor == "bf16"

    runner = ad2.build(trainable, strategy2)
    b = lm_batches(1)[0]
    runner.step(b, rng=jax.random.PRNGKey(0))
    mu = runner.state["opt_state"][0].mu
    assert mu["embed"]["embedding"].ndim == 1          # ZeRO flat
    assert mu["embed"]["embedding"].sharding.spec == P(("data", "seq"))
    assert mu["ln"]["scale"].ndim == 1 and \
        mu["ln"]["scale"].shape == (DIM,)              # replicated


def test_sequence_ssp_staleness_threads_to_runner():
    """PS(staleness>0) node configs under a parallel lowering reach the
    runner's host SSP gate (lowering-agnostic; without a coordination
    service it warns and runs lockstep)."""
    from autodist_tpu.strategy.ir import PSSynchronizer

    ad = AutoDist(SEQ_SPEC, "SequenceParallel", zero1=True)
    trainable = make_lm_trainable(sharded=True)
    strategy = ad.build_or_load_strategy(trainable)
    for nc in strategy.node_configs:
        nc.synchronizer = PSSynchronizer(staleness=2)
    runner = ad.build(trainable, strategy)
    assert runner.lowered.ssp_staleness == 2
    # no coordination service in this test -> gate disabled, lockstep
    assert runner._ssp is None
    m = runner.step(lm_batches(1)[0], rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_sequence_int8_ring_compressor_over_tuple_axes():
    """The stateful ppermute-ring compressor runs over the combined
    (data x seq) axis group (ring over the linearized 8-device group)."""
    ad = AutoDist(SEQ_SPEC, "SequenceParallel", compressor="int8_ring")
    trainable = make_lm_trainable(sharded=True, opt=optax.sgd(0.05))
    runner = ad.build(trainable)
    for b in lm_batches(2):
        m = runner.step(b, rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))
    for row in jax.tree.leaves(runner.state["sync_state"]):
        assert row.shape[0] == 8


# --------------------------------------------------------------------------- #
# ZeRO stages 2/3 on the 3D mesh (PR 6): goldens pinning loss/grad parity
# of the higher stages against the stage-0/1 reference, composed with
# dp x tp x vocab_parallel x bf16_ef, plus the non-divisible-leaf
# padding edge.  Stage 2 lowers identically to stage 1 (the U_FLAT
# scheme already reduce-scatters) so its parity is exact; stage 3 only
# reorders the same gather/scatter sums, so it is pinned at the same
# tolerance as the stage-1 goldens above.
# --------------------------------------------------------------------------- #
def _lm_cfg(vocab=32):
    from autodist_tpu.models.transformer import TransformerConfig

    return TransformerConfig(vocab_size=vocab, hidden_size=16, num_layers=2,
                             num_heads=2, mlp_dim=32, max_len=8,
                             dtype=jnp.float32, dropout_rate=0.0,
                             attention_dropout_rate=0.0)


def _make_lm(opt=None, vocab=32):
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable

    return make_pipeline_lm_trainable(_lm_cfg(vocab), opt or optax.sgd(0.05),
                                      jax.random.PRNGKey(0))


def _lm_token_batches(n, vocab=32):
    r = np.random.RandomState(3)
    out = []
    for _ in range(n):
        x = r.randint(0, vocab, (8, 8)).astype(np.int32)
        out.append({"x": x, "y": np.roll(x, -1, axis=1)})
    return out


_Z_SPECS = {
    "dp4": ({"topology": {"platform": "cpu", "num_devices": 8},
             "mesh": {"data": 4, "pipe": 2}}, 1, False),
    "dp2_tp2": ({"topology": {"platform": "cpu", "num_devices": 8},
                 "mesh": {"data": 2, "pipe": 2, "model": 2}}, 2, False),
    "dp2_tp2_vocab": ({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"data": 2, "pipe": 2, "model": 2}}, 2, True),
}


@pytest.mark.parametrize("mesh_key", sorted(_Z_SPECS))
@pytest.mark.parametrize("stage", [2, 3])
def test_pipeline_zero_stages_match_reference(mesh_key, stage):
    """Stages 2 and 3 reproduce the stage-0 AND stage-1 trajectories of
    the pipelined LM for dp in {2,4} x tp in {1,2} x vocab_parallel
    in {off,on}.  sgd at the TP-golden tolerance (repo precedent: adam's
    eps nonlinearity amplifies ulp-level fp reordering on near-zero
    grads; the adam-moment load-bearing coverage lives in the MLP and
    padding-edge tests below, where the sum order is identical)."""
    spec, tp, vocab_parallel = _Z_SPECS[mesh_key]
    bs = _lm_token_batches(3)

    def build(**kw):
        return AutoDist(spec, "Pipeline", num_microbatches=2,
                        tensor_parallel=tp, vocab_parallel=vocab_parallel,
                        **kw).build(_make_lm(optax.sgd(0.05)))

    r0 = build()
    r1 = build(zero_stage=1)
    rs = build(zero_stage=stage)
    for b in bs:
        m0 = r0.step(b, rng=jax.random.PRNGKey(0))
        r1.step(b, rng=jax.random.PRNGKey(0))
        ms = rs.step(b, rng=jax.random.PRNGKey(0))
        np.testing.assert_allclose(float(np.asarray(m0["loss"])),
                                   float(np.asarray(ms["loss"])),
                                   rtol=1e-5)
    assert_trees_close(rs.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)
    assert_trees_close(rs.get_params(), r1.get_params(), rtol=1e-5,
                       atol=1e-6)
    if stage >= 3:
        # stage-3 storage: non-tp stage leaves live as [C, padded]
        # flat rows sharded P(pipe, data); shared ones flat (pipe, data)
        ln = rs.state["params"]["shared"]["ln_final_scale"]
        assert ln.ndim == 1 and ln.sharding.spec == P(("pipe", "data"))


@pytest.mark.parametrize("stage", [2, 3])
def test_pipeline_zero_stages_with_bf16_ef_mix(stage):
    """The Parallax-style size split composes with the higher stages:
    large variables ZeRO at the requested stage, small ones bf16_ef-
    compressed — same mix at stage 1 is the bit-close reference (the
    compression error is identical; the stage only reorders exact
    sums)."""
    kw = dict(num_microbatches=2, zero_min_bytes=512,
              compressor="bf16_ef")
    r1 = AutoDist(PIPE_SPEC, "Pipeline", zero_stage=1, **kw).build(
        make_pipeline_trainable(optax.sgd(0.05)))
    rs = AutoDist(PIPE_SPEC, "Pipeline", zero_stage=stage, **kw).build(
        make_pipeline_trainable(optax.sgd(0.05)))
    r_plain = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2).build(
        make_pipeline_trainable(optax.sgd(0.05)))
    bs = pipe_batches(3)
    for b in bs:
        r1.step(b)
        rs.step(b)
        r_plain.step(b)
    assert_trees_close(rs.get_params(), r1.get_params(), rtol=1e-5,
                       atol=1e-6)
    # EF keeps the mixed run near the uncompressed one (loose bound)
    assert_trees_close(rs.get_params(), r_plain.get_params(), rtol=5e-2,
                       atol=5e-3)
    # the mix is heterogeneous: w [HID,HID] f32 = 256B < 512 threshold?
    # HID=8 -> w is 8*8*4 = 256B, b 32B: everything below 512 would be
    # uniform — assert the split actually split on this model.
    from autodist_tpu.strategy.ir import PSSynchronizer
    strat = AutoDist(PIPE_SPEC, "Pipeline", zero_stage=stage, **kw) \
        .build_or_load_strategy(make_pipeline_trainable())
    kinds = {n.var_name: isinstance(n.synchronizer, PSSynchronizer)
             for n in strat.node_configs}
    assert any(kinds.values()) and not all(kinds.values()), kinds
    ps_stages = {n.var_name: n.synchronizer.zero_stage
                 for n in strat.node_configs
                 if isinstance(n.synchronizer, PSSynchronizer)}
    assert set(ps_stages.values()) == {stage}


def test_pipeline_zero3_non_divisible_leaf_padding():
    """The padding edge: stage-leaf chunk sizes that do not divide the
    data-replica count pad per chunk ([C, padded_chunk] rows), train
    bit-close to the unsharded run, and fetch back unpadded."""
    HID_ODD = 7   # chunk elems 49 / 7: neither divides dp=2

    def make(opt=None):
        r = np.random.RandomState(0)
        stacked = {"w": jnp.asarray(r.randn(S_STAGES, HID_ODD, HID_ODD)
                                    * 0.5, jnp.float32),
                   "b": jnp.asarray(r.randn(S_STAGES, HID_ODD) * 0.1,
                                    jnp.float32)}
        return PipelineTrainable(mlp_stage, stacked, mse_head,
                                 opt or optax.adam(1e-2),
                                 num_stages=S_STAGES)

    def batches(n):
        r = np.random.RandomState(2)
        return [{"x": r.randn(8, HID_ODD).astype(np.float32),
                 "y": r.randn(8, HID_ODD).astype(np.float32)}
                for _ in range(n)]

    r0 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2).build(make())
    r3 = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2,
                  zero_stage=3).build(make())
    for b in batches(3):
        r0.step(b)
        r3.step(b)
    assert_trees_close(r3.get_params(), r0.get_params(), rtol=1e-5,
                       atol=1e-6)
    # stored padded: w chunk = 49 elems -> 50 wide over dp=2
    w = r3.state["params"]["w"]
    assert w.shape == (S_STAGES, 50), w.shape
    assert r3.get_params()["w"].shape == (S_STAGES, HID_ODD, HID_ODD)


def test_expert_compressor_on_sharded_vars_sizes_ef_locally():
    """Stateful compressor on expert-SHARDED variables: the EF residual
    row is sized from the per-device shard (global size / E), not the
    global size — and training runs (pins the local-size fix)."""
    ad = AutoDist(EXPERT_SPEC, "ExpertParallel", compressor="bf16_ef")
    trainable = make_moe_trainable(opt=optax.sgd(0.05))
    runner = ad.build(trainable)
    for b in moe_batches(2):
        m = runner.step(b, rng=jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(m["loss"])))
    sync = runner.state["sync_state"]
    assert sync, "stateful compressor rows expected"
    # expert_wi global [4, 8, 16] = 512 elems over 4 expert shards ->
    # local 128-length residual rows
    wi_rows = sync["moe/expert_wi"]
    assert wi_rows.shape == (8, 128), wi_rows.shape
    # replicated gate [8, 4] = 32 elems -> full-size rows
    assert sync["moe/gate"].shape == (8, 32)
