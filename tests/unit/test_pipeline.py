"""Pipeline parallelism: the GPipe schedule must compute exactly what the
equivalent sequential stacked-stage model computes — forward and through
training steps."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.parallel.pipeline import (last_stage_value, lower_pipeline,
                                            pipeline_apply)

S = 4          # pipeline stages
HID = 8


def stage_fn(params, x):
    """One MLP stage: x @ w + b, relu."""
    return jax.nn.relu(x @ params["w"] + params["b"])


def make_stacked_params(seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(S, HID, HID) * 0.5, jnp.float32),
            "b": jnp.asarray(r.randn(S, HID) * 0.1, jnp.float32)}


def sequential_forward(stacked, x):
    for i in range(S):
        x = stage_fn(jax.tree.map(lambda p: p[i], stacked), x)
    return x


@pytest.mark.parametrize("num_microbatches", [1, 2, 4])
def test_pipeline_forward_matches_sequential(num_microbatches):
    mesh = jax.make_mesh((4,), ("pipe",))
    stacked = make_stacked_params()
    x = jnp.asarray(np.random.RandomState(1).randn(8, HID), jnp.float32)

    def run(stacked, x):
        sp = jax.tree.map(lambda p: p[0], stacked)
        out = pipeline_apply(stage_fn, sp, x, axis_name="pipe",
                             num_microbatches=num_microbatches)
        return last_stage_value(out, "pipe")

    fn = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("pipe"), stacked), P()),
        out_specs=P(), check_vma=False))
    out = fn(stacked, x)
    ref = sequential_forward(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_training_matches_sequential():
    """Full train steps through lower_pipeline == sequential training."""
    mesh = jax.make_mesh((2, 4), ("data", "pipe"))
    stacked = make_stacked_params()

    def loss_head(outputs, batch):
        l = jnp.mean((outputs - batch["y"]) ** 2)
        return l, {}

    opt = optax.sgd(0.05)
    init_fn, step_fn, shardings = lower_pipeline(
        stage_fn, stacked, loss_head, opt, mesh, num_microbatches=2)
    state = init_fn(stacked)

    r = np.random.RandomState(2)
    batches = [{"x": r.randn(8, HID).astype(np.float32),
                "y": r.randn(8, HID).astype(np.float32)} for _ in range(3)]

    # sequential reference
    ref_params = stacked
    ref_opt = opt.init(stacked)

    def ref_loss(p, b):
        return jnp.mean((sequential_forward(p, b["x"]) - b["y"]) ** 2)

    losses_pipe, losses_ref = [], []
    for b in batches:
        gb = jax.device_put(b, NamedSharding(mesh, P("data")))
        state, metrics = step_fn(state, gb, jax.random.PRNGKey(0))
        losses_pipe.append(float(metrics["loss"]))

        jb = jax.tree.map(jnp.asarray, b)
        losses_ref.append(float(ref_loss(ref_params, jb)))
        g = jax.grad(ref_loss)(ref_params, jb)
        upd, ref_opt = opt.update(g, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    np.testing.assert_allclose(losses_pipe, losses_ref, rtol=1e-4, atol=1e-5)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        jax.device_get(state["params"]), jax.device_get(ref_params))
