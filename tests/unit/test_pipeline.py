"""Pipeline parallelism: the GPipe schedule must compute exactly what the
equivalent sequential stacked-stage model computes — forward and through
training steps."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.parallel.pipeline import (last_stage_value, lower_pipeline,
                                            pipeline_apply)

S = 4          # pipeline stages
HID = 8


def stage_fn(params, x):
    """One MLP stage: x @ w + b, relu."""
    return jax.nn.relu(x @ params["w"] + params["b"])


def make_stacked_params(seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(S, HID, HID) * 0.5, jnp.float32),
            "b": jnp.asarray(r.randn(S, HID) * 0.1, jnp.float32)}


def sequential_forward(stacked, x):
    for i in range(S):
        x = stage_fn(jax.tree.map(lambda p: p[i], stacked), x)
    return x


@pytest.mark.parametrize("num_microbatches", [1, 2, 4])
def test_pipeline_forward_matches_sequential(num_microbatches):
    mesh = jax.make_mesh((4,), ("pipe",))
    stacked = make_stacked_params()
    x = jnp.asarray(np.random.RandomState(1).randn(8, HID), jnp.float32)

    def run(stacked, x):
        sp = jax.tree.map(lambda p: p[0], stacked)
        out = pipeline_apply(stage_fn, sp, x, axis_name="pipe",
                             num_microbatches=num_microbatches)
        return last_stage_value(out, "pipe")

    fn = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("pipe"), stacked), P()),
        out_specs=P(), check_vma=False))
    out = fn(stacked, x)
    ref = sequential_forward(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_training_matches_sequential():
    """Full train steps through lower_pipeline == sequential training."""
    mesh = jax.make_mesh((2, 4), ("data", "pipe"))
    stacked = make_stacked_params()

    def loss_head(outputs, batch):
        l = jnp.mean((outputs - batch["y"]) ** 2)
        return l, {}

    opt = optax.sgd(0.05)
    init_fn, step_fn, shardings = lower_pipeline(
        stage_fn, stacked, loss_head, opt, mesh, num_microbatches=2)
    state = init_fn(stacked)

    r = np.random.RandomState(2)
    batches = [{"x": r.randn(8, HID).astype(np.float32),
                "y": r.randn(8, HID).astype(np.float32)} for _ in range(3)]

    # sequential reference
    ref_params = stacked
    ref_opt = opt.init(stacked)

    def ref_loss(p, b):
        return jnp.mean((sequential_forward(p, b["x"]) - b["y"]) ** 2)

    losses_pipe, losses_ref = [], []
    for b in batches:
        gb = jax.device_put(b, NamedSharding(mesh, P("data")))
        state, metrics = step_fn(state, gb, jax.random.PRNGKey(0))
        losses_pipe.append(float(metrics["loss"]))

        jb = jax.tree.map(jnp.asarray, b)
        losses_ref.append(float(ref_loss(ref_params, jb)))
        g = jax.grad(ref_loss)(ref_params, jb)
        upd, ref_opt = opt.update(g, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    np.testing.assert_allclose(losses_pipe, losses_ref, rtol=1e-4, atol=1e-5)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        jax.device_get(state["params"]), jax.device_get(ref_params))


# ---------------- interleaved virtual stages (round-4) ------------------ #
def test_interleaved_schedule_shrinks_bubble():
    """The closed-form schedule's bubble: interleaving V chunks per
    device beats GPipe with V-chunk fused stages, in chunk-time units
    (num_ticks(M,n,V) = MV + n - 1  <  V*(M + n - 1) for V>1)."""
    from autodist_tpu.parallel.pipeline import bubble_fraction, num_ticks

    M, n = 8, 4
    assert num_ticks(M, n, 1) == M + n - 1
    for V in (2, 4):
        # same total work (M*V chunk-times useful), fewer total ticks
        assert num_ticks(M, n, V) < V * num_ticks(M, n, 1)
        assert bubble_fraction(M, n, V) < bubble_fraction(M, n, 1)
    # closed form: (n-1)/(MV + n - 1)
    assert num_ticks(M, n, 2) == M * 2 + n - 1


def test_interleaved_schedule_is_conflict_free():
    """No device processes two (microbatch, chunk) pairs in one tick, and
    every pair is processed exactly once at its start tick."""
    from autodist_tpu.parallel.pipeline import num_ticks, start_tick

    n, V, M = 4, 2, 8
    seen = {}
    for m in range(M):
        for c in range(n * V):
            t = start_tick(m, c, num_devices=n, virtual_stages=V)
            dev = c % n
            assert (t, dev) not in seen, f"collision at {(t, dev)}"
            seen[(t, dev)] = (m, c)
            if c > 0:
                assert t == start_tick(m, c - 1, num_devices=n,
                                       virtual_stages=V) + 1
    assert max(t for t, _ in seen) + 1 == num_ticks(M, n, V)


def test_interleaved_forward_matches_sequential():
    """V=2 interleaved over 8 chunks on 4 devices == sequential 8-stage
    forward."""
    from autodist_tpu.parallel.pipeline import (chunk_permutation,
                                                pipeline_apply)

    n, V = 4, 2
    C = n * V
    r = np.random.RandomState(0)
    logical = {"w": jnp.asarray(r.randn(C, HID, HID) * 0.3, jnp.float32),
               "b": jnp.asarray(r.randn(C, HID) * 0.1, jnp.float32)}
    x = jnp.asarray(np.random.RandomState(1).randn(8, HID), jnp.float32)

    ref = x
    for i in range(C):
        ref = stage_fn(jax.tree.map(lambda p: p[i], logical), ref)

    perm = chunk_permutation(n, V)
    storage = jax.tree.map(lambda p: p[perm], logical)
    mesh = jax.make_mesh((n,), ("pipe",))

    def run(storage, x):
        local = storage  # [V, ...] per device under P("pipe")
        out = pipeline_apply(stage_fn, local, x, axis_name="pipe",
                             num_microbatches=2, virtual_stages=V)
        return last_stage_value(out, "pipe")

    fn = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("pipe"), storage), P()),
        out_specs=P(), check_vma=False))
    out = fn(storage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interleaved_training_matches_sequential():
    """Full train steps with virtual_stages=2 == sequential training of
    the 8-chunk model (params fetched back in logical order)."""
    from autodist_tpu.parallel.pipeline import _build_pipeline

    n, V = 4, 2
    C = n * V
    mesh = jax.make_mesh((2, n), ("data", "pipe"))
    r = np.random.RandomState(3)
    logical = {"w": jnp.asarray(r.randn(C, HID, HID) * 0.3, jnp.float32),
               "b": jnp.asarray(r.randn(C, HID) * 0.1, jnp.float32)}

    def loss_head(outputs, batch):
        return jnp.mean((outputs - batch["y"]) ** 2), {}

    opt = optax.sgd(0.05)
    built = _build_pipeline(stage_fn, logical, loss_head, opt, mesh,
                            num_microbatches=2, virtual_stages=V)
    state = built.init_fn(logical)

    batches = [{"x": r.randn(8, HID).astype(np.float32),
                "y": r.randn(8, HID).astype(np.float32)} for _ in range(3)]
    ref_params, ref_opt = logical, opt.init(logical)

    def seq_loss(p, b):
        h = b["x"]
        for i in range(C):
            h = stage_fn(jax.tree.map(lambda q: q[i], p), h)
        return jnp.mean((h - b["y"]) ** 2)

    for b in batches:
        gb = jax.device_put(b, NamedSharding(mesh, P("data")))
        state, metrics = built.step_fn(state, gb, jax.random.PRNGKey(0))
        jb = jax.tree.map(jnp.asarray, b)
        g = jax.grad(seq_loss)(ref_params, jb)
        upd, ref_opt = opt.update(g, ref_opt, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    got = jax.device_get(built.unpad_params(state["params"]))
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        got, jax.device_get(ref_params))


def test_pipeline_pytree_activations_and_stage_aux():
    """Dict activations flow through the ring; per-stage aux losses
    accumulate (the non-last-stage loss path) and match sequential."""
    from autodist_tpu.parallel.pipeline import _build_pipeline

    n = 4
    mesh = jax.make_mesh((n,), ("pipe",))
    r = np.random.RandomState(5)
    logical = {"w": jnp.asarray(r.randn(n, HID, HID) * 0.3, jnp.float32)}

    def tree_stage(params, act):
        h = jax.nn.relu(act["h"] @ params["w"])
        # mean-style aux: microbatch-mean == full-batch mean
        return {"h": h, "scale": act["scale"]}, jnp.mean(h ** 2)

    def loss_head(outputs, batch):
        return jnp.mean((outputs["h"] * outputs["scale"]
                         - batch["y"]) ** 2), {}

    opt = optax.sgd(0.05)
    built = _build_pipeline(tree_stage, logical, loss_head, opt, mesh,
                            num_microbatches=2, batch_key="x",
                            stage_aux=True)
    state = built.init_fn(logical)
    b = {"x": {"h": r.randn(8, HID).astype(np.float32),
               "scale": np.ones((8, 1), np.float32)},
         "y": r.randn(8, HID).astype(np.float32)}
    state, metrics = built.step_fn(state, jax.tree.map(jnp.asarray, b),
                                   jax.random.PRNGKey(0))

    # sequential reference: loss + sum of per-stage aux
    def seq(p, b):
        act = {"h": jnp.asarray(b["x"]["h"]),
               "scale": jnp.asarray(b["x"]["scale"])}
        aux = 0.0
        for i in range(n):
            act, a = tree_stage(jax.tree.map(lambda q: q[i], p), act)
            aux = aux + a
        l, _ = loss_head(act, {"y": jnp.asarray(b["y"])})
        return l + aux, (l, aux)

    (ref_total, (ref_l, ref_aux)), ref_g = jax.value_and_grad(
        seq, has_aux=True)(logical, b)
    np.testing.assert_allclose(float(np.asarray(metrics["loss"])),
                               float(ref_total), rtol=1e-4)
    np.testing.assert_allclose(float(np.asarray(metrics["aux_loss"])),
                               float(ref_aux), rtol=1e-4)
    # one sgd step equals the sequential gradient step
    expect = jax.tree.map(lambda p, g: p - 0.05 * g, logical, ref_g)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        jax.device_get(built.unpad_params(state["params"])),
        jax.device_get(expect))


def test_pipeline_stage_remat_matches_sequential():
    """jax.checkpoint inside stage_fn (the documented long-pipeline
    memory recipe) must not change numerics through the schedule."""
    mesh = jax.make_mesh((2, 4), ("data", "pipe"))
    stacked = make_stacked_params(7)

    remat_stage = jax.checkpoint(stage_fn)

    def loss_head(outputs, batch):
        return jnp.mean((outputs - batch["y"]) ** 2), {}

    opt = optax.sgd(0.05)
    init_fn, step_fn, _ = lower_pipeline(
        remat_stage, stacked, loss_head, opt, mesh, num_microbatches=2)
    state = init_fn(stacked)
    r = np.random.RandomState(8)
    b = {"x": r.randn(8, HID).astype(np.float32),
         "y": r.randn(8, HID).astype(np.float32)}
    gb = jax.device_put(b, NamedSharding(mesh, P("data")))
    state, metrics = step_fn(state, gb, jax.random.PRNGKey(0))

    ref_params, ref_opt = stacked, opt.init(stacked)

    def ref_loss(p, bb):
        return jnp.mean((sequential_forward(p, bb["x"]) - bb["y"]) ** 2)

    jb = jax.tree.map(jnp.asarray, b)
    g = jax.grad(ref_loss)(ref_params, jb)
    upd, ref_opt = opt.update(g, ref_opt, ref_params)
    expect = optax.apply_updates(ref_params, upd)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        jax.device_get(state["params"]), jax.device_get(expect))
