"""Pipeline dropout: per-(chunk, sample) rng threading.

Round-4 VERDICT Missing #6: pipeline stages could not use dropout.  The
fix keys each dropout mask on (global chunk index, global sample index)
— drawn per row — which makes the masks microbatching- and
data-sharding-invariant: the pipelined LM with dropout reproduces the
sequential execution (PipelineTrainable.loss) golden-exactly under a
fixed rng, for any num_microbatches.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig

pytestmark = pytest.mark.slow

CFG = TransformerConfig(
    vocab_size=64, hidden_size=16, num_layers=4, num_heads=2, mlp_dim=32,
    max_len=16, dtype=jnp.float32, dropout_rate=0.1,
    attention_dropout_rate=0.1)
SPEC = {"topology": {"platform": "cpu", "num_devices": 8},
        "mesh": {"data": 2, "pipe": 4}}


def batches(n, seed=0):
    r = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = r.randint(0, 64, (8, 16)).astype(np.int32)
        out.append({"x": x, "y": np.roll(x, -1, axis=1)})
    return out


def sequential_train(trainable, bs, keys):
    params = trainable.params
    opt_state = trainable.optimizer.init(params)
    for b, k in zip(bs, keys):
        jb = jax.tree.map(jnp.asarray, b)

        def loss_for(p):
            l, _, _ = trainable.loss(p, None, jb, k)
            return l

        g = jax.grad(loss_for)(params)
        upd, opt_state = trainable.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)
    return jax.device_get(params)


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pipeline_dropout_matches_sequential(microbatches):
    t = make_pipeline_lm_trainable(CFG, optax.sgd(0.1), rng=0)
    assert t.stage_rng
    runner = AutoDist(SPEC, "Pipeline",
                      num_microbatches=microbatches).build(t)
    bs = batches(2)
    keys = [jax.random.PRNGKey(100 + i) for i in range(len(bs))]
    for b, k in zip(bs, keys):
        m = runner.step(b, rng=k)
    assert np.isfinite(float(np.asarray(m["loss"])))

    ref = sequential_train(make_pipeline_lm_trainable(CFG, optax.sgd(0.1),
                                                      rng=0), bs, keys)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), ref)


def test_pipeline_dropout_is_active_and_eval_deterministic():
    """Dropout changes the training loss vs the deterministic config,
    and eval ignores it."""
    t = make_pipeline_lm_trainable(CFG, optax.sgd(0.1), rng=0)
    det_cfg = TransformerConfig(**{**CFG.__dict__, "dropout_rate": 0.0,
                                   "attention_dropout_rate": 0.0})
    t_det = make_pipeline_lm_trainable(det_cfg, optax.sgd(0.1), rng=0)
    b = batches(1)[0]
    r1 = AutoDist(SPEC, "Pipeline", num_microbatches=2).build(t)
    r2 = AutoDist(SPEC, "Pipeline", num_microbatches=2).build(t_det)
    l1 = float(np.asarray(r1.step(b, rng=jax.random.PRNGKey(7))["loss"]))
    l2 = float(np.asarray(r2.step(b, rng=jax.random.PRNGKey(7))["loss"]))
    assert abs(l1 - l2) > 1e-6, "dropout must perturb the training loss"
    # eval path runs deterministic: same metrics under different rngs
    e1 = r1.eval_step(b, rng=jax.random.PRNGKey(1))
    e2 = r1.eval_step(b, rng=jax.random.PRNGKey(2))
    np.testing.assert_allclose(float(np.asarray(e1["loss"])),
                               float(np.asarray(e2["loss"])), rtol=1e-6)


def test_pipeline_dropout_with_virtual_stages_matches_sequential():
    """V=2 interleaving: the c_global = v*n + device mapping against the
    interleaved storage permutation must agree with the sequential
    chunk order."""
    cfg8 = TransformerConfig(**{**CFG.__dict__, "num_layers": 8})
    t = make_pipeline_lm_trainable(cfg8, optax.sgd(0.1), rng=0)
    runner = AutoDist(SPEC, "Pipeline", num_microbatches=4,
                      virtual_stages=2).build(t)
    bs = batches(2)
    keys = [jax.random.PRNGKey(50 + i) for i in range(len(bs))]
    for b, k in zip(bs, keys):
        runner.step(b, rng=k)

    ref = sequential_train(
        make_pipeline_lm_trainable(cfg8, optax.sgd(0.1), rng=0), bs, keys)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), ref)


def test_pipeline_dropout_with_grad_accumulation_matches_full_batch():
    """accum=2 x dropout: slices share the step rng and rows continue
    globally, so the accumulated step reproduces the single full-batch
    step exactly (mean loss => mean of slice grads == full grad)."""
    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t = make_pipeline_lm_trainable(CFG, optax.sgd(0.1), rng=0)
    runner = AutoDist(
        SPEC, GradAccumulation(Pipeline(num_microbatches=2),
                               steps=2)).build(t)
    b = batches(1, seed=7)[0]  # [8, 16] -> 2 accum slices of 4 per shard
    k = jax.random.PRNGKey(9)
    runner.step(b, rng=k)

    ref = sequential_train(
        make_pipeline_lm_trainable(CFG, optax.sgd(0.1), rng=0), [b], [k])
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5),
        runner.get_params(), ref)
