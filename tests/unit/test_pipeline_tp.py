"""Tensor parallelism inside pipeline stages (the dp×pp×tp composition).

Round-5 VERDICT's missing composition: a single mesh factored as
``(data, pipe, model)`` with Megatron-sharded matmuls per pipeline
stage.  Correctness is pinned the way round 5 pinned ZeRO
(``test_parallel_zero.py``): goldens against the *sequential
single-device* reference — ``PipelineTrainable.loss`` runs the stages
in order on full parameters with zero collectives — for ``tp ∈ {1, 2}``
across the microbatch / virtual-stage combinations the plain pipeline
tests cover, plus composition with ZeRO-1 and a compressor.

SGD goldens are tight (1e-5): tensor parallelism only re-orders the
matmul contraction sums.  Adam runs assert the sharding *layout* and use
a loose bound — adam's ``m/sqrt(v)`` amplifies legitimate fp-order noise
on near-zero gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig

CFG = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=2, mlp_dim=32, max_len=8,
                        dtype=jnp.float32, dropout_rate=0.0,
                        attention_dropout_rate=0.0)
SPEC_3D = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 2, "pipe": 2, "model": 2}}


def make_lm(opt=None, cfg=CFG, seed=0):
    return make_pipeline_lm_trainable(cfg, opt or optax.sgd(0.05),
                                      jax.random.PRNGKey(seed))


def lm_batches(n, seed=0):
    r = np.random.RandomState(seed)
    return [{"x": r.randint(0, CFG.vocab_size, (8, 8)).astype(np.int32),
             "y": r.randint(0, CFG.vocab_size, (8, 8)).astype(np.int32)}
            for _ in range(n)]


def sequential_train(trainable, batches):
    """Single-device reference: the trainable's own sequential loss."""
    params = trainable.params
    opt_state = trainable.optimizer.init(params)
    losses = []
    for b in batches:
        def loss_for(p):
            l, _, _ = trainable.loss(p, None, jax.tree.map(jnp.asarray, b),
                                     jax.random.PRNGKey(0))
            return l
        losses.append(float(loss_for(params)))
        g = jax.grad(loss_for)(params)
        upd, opt_state = trainable.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)
    return jax.device_get(params), losses


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


def test_tp2_pipeline_matches_sequential_reference():
    """The headline golden: dp=2 x pp=2 x tp=2 training of the pipelined
    transformer LM reproduces the sequential single-device reference —
    losses AND parameters — with the stage weights genuinely stored
    Megatron-sharded over the model axis."""
    runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                      tensor_parallel=2).build(make_lm())
    bs = lm_batches(3)
    losses = [float(np.asarray(runner.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in bs]
    ref_params, ref_losses = sequential_train(make_lm(), bs)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    assert_trees_close(runner.get_params(), ref_params)

    stages = runner.state["params"]["stages"]
    # column-parallel: qkv heads dim / wi features dim carry 'model'
    assert stages["attention"]["qkv"]["kernel"].sharding.spec == \
        P("pipe", None, None, "model")
    assert stages["mlp"]["wi"]["kernel"].sharding.spec == \
        P("pipe", None, "model")
    # row-parallel: out heads dim / wo features dim carry 'model'
    assert stages["mlp"]["wo"]["kernel"].sharding.spec == \
        P("pipe", "model")
    # model-replicated: layer norms stay pipe-only
    assert stages["ln_mlp"]["scale"].sharding.spec == P("pipe")


@pytest.mark.slow
@pytest.mark.parametrize("num_microbatches", [1, 4])
def test_tp2_microbatch_counts_match_sequential(num_microbatches):
    runner = AutoDist(SPEC_3D, "Pipeline",
                      num_microbatches=num_microbatches,
                      tensor_parallel=2).build(make_lm())
    bs = lm_batches(2)
    losses = [float(np.asarray(runner.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in bs]
    ref_params, ref_losses = sequential_train(make_lm(), bs)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    assert_trees_close(runner.get_params(), ref_params)


@pytest.mark.slow
def test_tp2_interleaved_virtual_stages_match_sequential():
    """Megatron interleaving (V=2) composes with Megatron TP: 4 logical
    stages on pipe=2 x model=2, bit-parity preserved."""
    cfg4 = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=4,
                             num_heads=2, mlp_dim=32, max_len=8,
                             dtype=jnp.float32, dropout_rate=0.0,
                             attention_dropout_rate=0.0)
    runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=4,
                      virtual_stages=2, tensor_parallel=2).build(
                          make_lm(cfg=cfg4, seed=1))
    bs = lm_batches(2)
    losses = [float(np.asarray(runner.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in bs]
    ref_params, ref_losses = sequential_train(make_lm(cfg=cfg4, seed=1), bs)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    assert_trees_close(runner.get_params(), ref_params)


@pytest.mark.slow
def test_tp2_zero1_matches_plain_and_shards_state():
    """ZeRO-1 composes with tp: model-replicated stage vars (layer norms,
    row biases) and the shared embedding get flat-sharded moments; tp-
    sharded vars keep their (pipe, model) state sharding (the PS request
    degrades — state already shards with the parameter); numerics match
    the plain tp run tight under sgd."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2).build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, zero1=True).build(make_lm())
    for b in lm_batches(3):
        r0.step(b, rng=jax.random.PRNGKey(0))
        r1.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(r1.get_params(), r0.get_params())

    ra = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, zero1=True).build(
                      make_lm(optax.adam(1e-2)))
    ra.step(lm_batches(1)[0], rng=jax.random.PRNGKey(0))
    mu = ra.state["opt_state"][0].mu
    # tp-sharded var: moment shards exactly like the parameter
    assert mu["stages"]["attention"]["qkv"]["kernel"].sharding.spec == \
        P("pipe", None, None, "model")
    # model-replicated stage var: ZeRO flat over (pipe x data)
    ln = mu["stages"]["ln_mlp"]["scale"]
    assert ln.ndim == 1 and ln.sharding.spec == P(("pipe", "data"))
    # shared var: ZeRO flat over (pipe x data) jointly
    emb = mu["shared"]["embedding"]
    assert emb.ndim == 1 and emb.sharding.spec == P(("pipe", "data"))


def test_tp2_int8_quantized_allreduce_composes():
    """int8 quantized gradient allreduce (EQuARX-style,
    kernel/compressor.py) composed with tp=2 — the compressor matrix
    beyond bf16_ef: the shared-scale ``int8_ef`` psum and the true
    int8-wire ``int8_ring`` ppermute ring both run over the data axis
    while activations all-reduce over the model axis, stay close to the
    uncompressed run, and size their EF residuals from the
    (pipe × model)-local shard."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2).build(make_lm())
    l0, p0 = [], None
    for b in lm_batches(2):
        l0.append(float(np.asarray(
            r0.step(b, rng=jax.random.PRNGKey(0))["loss"])))
    p0 = r0.get_params()
    for comp in ("int8_ef", "int8_ring"):
        r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                      tensor_parallel=2, compressor=comp).build(make_lm())
        l1 = [float(np.asarray(r1.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in lm_batches(2)]
        # int8 has ~2 decimal digits of mantissa; error feedback keeps
        # the *trajectory* close, not the per-step bits.
        np.testing.assert_allclose(l1, l0, rtol=5e-2, atol=5e-2,
                                   err_msg=comp)
        assert_trees_close(r1.get_params(), p0, rtol=5e-2, atol=5e-3)
        sync = r1.state["sync_state"]
        # qkv kernel global C*3*nh*hd*H = 2*3*2*8*16 = 1536 over
        # pipe(2) x model(2) shards -> 384-length local residual rows,
        # one per device.
        assert sync["stages/attention/qkv/kernel"].shape == (8, 384), comp
        r1.close()
    r0.close()


@pytest.mark.slow
def test_tp2_compressor_runs_close_and_sizes_ef_locally():
    """bf16_ef over the data axis composes with tp; EF residual rows are
    sized from the (pipe x model)-local shard, one row per device."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2).build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, compressor="bf16_ef").build(make_lm())
    for b in lm_batches(2):
        r0.step(b, rng=jax.random.PRNGKey(0))
        r1.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=5e-2,
                       atol=5e-3)
    sync = r1.state["sync_state"]
    # qkv kernel global C*H*3*nh*hd = 2*16*3*2*8 = 1536 over
    # pipe(2) x model(2) shards -> 384-length local residual rows.
    assert sync["stages/attention/qkv/kernel"].shape == (8, 384)


def test_tp_strategy_ir_round_trip_and_validation():
    """The tensor_parallel knob and per-variable model specs are part of
    the serialized strategy (chief→worker handoff), and the builder
    rejects meshes/namings that cannot realize the declared degree."""
    from autodist_tpu.strategy.ir import Strategy
    from autodist_tpu.strategy.parallel_builders import Pipeline
    from autodist_tpu.resource import ResourceSpec

    ad = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2)
    strategy = ad.build_or_load_strategy(make_lm())
    assert strategy.graph_config.parallel["tensor_parallel"] == 2
    clone = Strategy.from_json(strategy.to_json())
    by_name = {n.var_name: n for n in clone.node_configs}
    assert by_name["stages/mlp/wo/kernel"].partitioner.spec == \
        ["pipe", "model", None]
    assert by_name["stages/ln_mlp/scale"].partitioner.spec == ["pipe", None]

    # no model axis in the mesh -> builder refuses
    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"data": 4, "pipe": 2}})
    with pytest.raises(ValueError, match="model"):
        Pipeline(num_microbatches=2, tensor_parallel=2).build(make_lm(), rs)

    # naming that matches no tp rule -> builder refuses (silent plain
    # pipelining on a declared model axis would be a lie)
    from autodist_tpu import PipelineTrainable
    stacked = {"w": jnp.zeros((2, 8, 8)), "b": jnp.zeros((2, 8))}
    mlp = PipelineTrainable(lambda p, x: x @ p["w"] + p["b"], stacked,
                            lambda o, b: (jnp.mean(o), {}), optax.sgd(0.1),
                            num_stages=2)
    rs3 = ResourceSpec(SPEC_3D)
    with pytest.raises(ValueError, match="no stage variable"):
        Pipeline(num_microbatches=2, tensor_parallel=2).build(mlp, rs3)


def test_factor_3d_and_resource_three_d():
    """resource.factor_3d: dp·pp·tp == num_devices validation and the
    canonical axis order (model innermost)."""
    from autodist_tpu.resource import ResourceSpec, factor_3d

    assert factor_3d(8, pipe=2, model=2) == {"data": 2, "pipe": 2,
                                             "model": 2}
    assert list(factor_3d(8, pipe=2, model=2)) == ["data", "pipe", "model"]
    assert factor_3d(4, pipe=4) == {"pipe": 4}
    assert factor_3d(8, pipe=2, model=2, data=2)["data"] == 2
    with pytest.raises(ValueError, match="!="):
        factor_3d(8, pipe=2, model=2, data=4)
    with pytest.raises(ValueError, match="factor"):
        factor_3d(8, pipe=3)

    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": factor_3d(8, pipe=2, model=2)})
    assert rs.three_d() == (2, 2, 2)
    seq = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                        "mesh": {"data": 2, "seq": 4}})
    with pytest.raises(ValueError, match="seq"):
        seq.three_d()


def test_cost_model_prices_tp_collectives_and_ranks_degrees():
    """The cost model sees tp: stage state shrinks by the tp degree and
    the per-stage Megatron activation all-reduces are priced, so
    auto_strategy can rank tensor_parallel degrees on a topology."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t1, t2 = make_lm(), make_lm()
    for t in (t1, t2):
        t.tokens_per_step = 4096
        t.act_bytes_per_token = 64.0
    rs = ResourceSpec(SPEC_3D)
    cm = CostModel(rs)
    s1 = Pipeline(num_microbatches=2).build(t1, rs)
    s2 = Pipeline(num_microbatches=2, tensor_parallel=2).build(t2, rs)
    c1 = cm.strategy_cost(t1, s1)
    c2 = cm.strategy_cost(t2, s2)
    # tp halves the tp-sharded stage state per device...
    assert c2.mem_bytes_per_device < c1.mem_bytes_per_device
    # ...and pays for it with the per-stage model-axis collectives.
    assert c2.num_collectives > c1.num_collectives
    assert c2.comm_bytes > c1.comm_bytes
