"""Per-collective precision policy (PR 8): the Strategy IR slot per
collective boundary (grad / tp_psum / vocab_stats / zero3_gather),
EQuARX-style quantization *inside* the collectives.

Pinned here:

* **Goldens** — int8/bf16 policies on the TP activation psums, the
  vocab epilogue, and the ZeRO-3 gathers stay within a pinned
  per-boundary-class tolerance of the fp32 trajectory across
  tp ∈ {1, 2} × vocab_parallel × zero_stage ∈ {1, 3}; a policy whose
  slots touch no boundary of the program (tp_psum at tp=1) reproduces
  the fp32 trajectory *bit-exactly* — narrowing is per-boundary, never
  ambient.
* **Backward compat** — a pre-PR-8 strategy JSON (no precision fields)
  round-trips byte-stably through the IR and lowers with
  fp32-everywhere semantics; hand-edited unknown precision values are
  rejected with the named ``UnknownPrecisionError``.
* **Cost model** — a quantized candidate outranks its fp32 sibling
  exactly when the bytes saved outweigh the calibrated q/dq compute
  (pinned in BOTH directions), and the ``"quant"`` calibration section
  merges like the ``"link"`` constants.
* **Telemetry schema gate** — a run annotated with a precision policy
  but missing the per-boundary ``precision/<boundary>_bits`` gauges
  fails ``tools/telemetry_report.py --check``.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, PipelineTrainable
from autodist_tpu.kernel.quantize import UnknownPrecisionError
from autodist_tpu.parallel.tensor import column_parallel, row_parallel
from autodist_tpu.strategy.ir import (PRECISION_BOUNDARIES, Strategy,
                                      normalize_precision)

SPEC_3D = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 2, "pipe": 2, "model": 2}}
SPEC_DP = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 4, "pipe": 2}}

HID, FF, C = 8, 16, 4


def _mlp_trainable():
    r = np.random.RandomState(0)
    stacked = {
        "wi": {"kernel": jnp.asarray(r.randn(C, HID, FF) * 0.3,
                                     jnp.float32),
               "bias": jnp.zeros((C, FF), jnp.float32)},
        "wo": {"kernel": jnp.asarray(r.randn(C, FF, HID) * 0.3,
                                     jnp.float32),
               "bias": jnp.zeros((C, HID), jnp.float32)},
    }

    def stage(p, x, model_axis=None, comm_overlap=None):
        h = jax.nn.relu(column_parallel(x, p["wi"]["kernel"],
                                        p["wi"]["bias"],
                                        model_axis=model_axis,
                                        comm_overlap=comm_overlap))
        return row_parallel(h, p["wo"]["kernel"], p["wo"]["bias"],
                            model_axis=model_axis,
                            comm_overlap=comm_overlap)

    def head(outputs, batch):
        return jnp.mean((outputs - batch["y"]) ** 2), {}

    return PipelineTrainable(stage, stacked, head, optax.sgd(0.05),
                             num_stages=C)


def _mlp_batches(n=3):
    r = np.random.RandomState(7)
    return [{"x": r.randn(8, HID).astype(np.float32),
             "y": r.randn(8, HID).astype(np.float32)} for _ in range(n)]


_trajectories: dict = {}


def _mlp_trajectory(tp, zero_stage, precision, strategy_json=None):
    """Losses + final params of 3 steps; memoized per config so every
    quantized run diffs against one shared fp32 baseline."""
    key = (tp, zero_stage, json.dumps(precision, sort_keys=True)
           if isinstance(precision, dict) else precision,
           strategy_json is not None)
    if key in _trajectories:
        return _trajectories[key]
    spec = SPEC_3D if tp > 1 else SPEC_DP
    trainable = _mlp_trainable()
    ad = AutoDist(spec, "Pipeline", num_microbatches=2, virtual_stages=2,
                  tensor_parallel=tp, zero_stage=zero_stage,
                  collective_precision=precision)
    strategy = (Strategy.from_json(strategy_json) if strategy_json
                else ad.build_or_load_strategy(trainable))
    runner = ad.build(trainable, strategy)
    try:
        losses = [float(np.asarray(
            runner.step(b, rng=jax.random.PRNGKey(0))["loss"]))
            for b in _mlp_batches()]
        params = jax.device_get(runner.get_params())
    finally:
        runner.close()
    _trajectories[key] = (losses, params, strategy)
    return _trajectories[key]


def _assert_close_trajectory(base, quant, loss_atol, param_atol):
    for lb, lq in zip(base[0], quant[0]):
        assert abs(lb - lq) <= loss_atol, (base[0], quant[0])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=param_atol, rtol=0),
        base[1], quant[1])


# Pinned convergence-neutral tolerances per boundary class, against the
# fp32 trajectory of the SAME config (3 sgd steps of the toy MLP /
# LM).  bf16 carries ~3 decimal digits, int8 ~2; the zero3 gather
# quantizes parameters themselves, hence the wider pin.
TP_PSUM_TOL = {"bf16": (5e-3, 5e-3), "int8": (2e-2, 2e-2)}
ZERO3_TOL = (3e-2, 3e-2)
VOCAB_TOL = {"bf16": (3e-2, 2e-2), "int8": (6e-2, 2e-2)}


@pytest.mark.parametrize("tp,zero_stage", [(2, 1), (2, 3), (1, 1), (1, 3)])
@pytest.mark.parametrize("prec", ["bf16", "int8"])
def test_policy_goldens_vs_fp32_trajectory(tp, zero_stage, prec):
    """tp × zero_stage × precision: the narrowed trajectory stays within
    the pinned tolerance of fp32 — and moves AT ALL only when the
    policy's slots touch a boundary the program emits."""
    base = _mlp_trajectory(tp, zero_stage, None)
    quant = _mlp_trajectory(tp, zero_stage, prec)
    loss_atol = max(TP_PSUM_TOL[prec][0],
                    ZERO3_TOL[0] if zero_stage >= 3 else 0.0)
    param_atol = max(TP_PSUM_TOL[prec][1],
                     ZERO3_TOL[1] if zero_stage >= 3 else 0.0)
    _assert_close_trajectory(base, quant, loss_atol, param_atol)


def test_policy_without_matching_boundary_is_bit_exact():
    """tp_psum/vocab_stats at tp=1: no model axis, no policied
    collective — the trajectory must be IDENTICAL to fp32 (the
    'defaults to today's behavior' contract at slot granularity)."""
    base = _mlp_trajectory(1, 1, None)
    scoped = _mlp_trajectory(1, 1, {"tp_psum": "int8",
                                    "vocab_stats": "int8"})
    assert base[0] == scoped[0]
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), base[1], scoped[1])


def test_zero3_gather_slot_narrows_dp_pipeline():
    """zero3_gather alone on the dp×pp mesh (every stage leaf genuinely
    flat-sharded): quantized parameter gathers + cotangent scatters stay
    within the pinned zero3 tolerance."""
    base = _mlp_trajectory(1, 3, None)
    quant = _mlp_trajectory(1, 3, {"zero3_gather": "int8"})
    _assert_close_trajectory(base, quant, *ZERO3_TOL)


# ---------------------------------------------------------------------- #
# Vocab epilogue goldens (the pipelined transformer LM, tp=2)
# ---------------------------------------------------------------------- #
_lm_runs: dict = {}


def _lm_trajectory(precision, zero_stage=0):
    key = (precision, zero_stage)
    if key in _lm_runs:
        return _lm_runs[key]
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=33, hidden_size=16, num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    trainable = make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                           jax.random.PRNGKey(0))
    runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                      tensor_parallel=2, vocab_parallel=True,
                      zero_stage=zero_stage,
                      collective_precision=precision).build(trainable)
    r = np.random.RandomState(5)
    try:
        losses = []
        for _ in range(3):
            b = {"x": r.randint(0, 33, (8, 8)).astype(np.int32),
                 "y": r.randint(0, 33, (8, 8)).astype(np.int32)}
            losses.append(float(np.asarray(
                runner.step(b, rng=jax.random.PRNGKey(0))["loss"])))
    finally:
        runner.close()
    _lm_runs[key] = losses
    return losses


@pytest.mark.parametrize("prec", ["bf16", "int8"])
def test_vocab_epilogue_goldens(prec):
    """int8/bf16 on the vocab-parallel epilogue (stat psums, pmax,
    backward hidden-cotangent psum — odd vocab 33 exercises the padded
    shard): losses track the fp32-policy trajectory within the pin."""
    base = _lm_trajectory(None)
    quant = _lm_trajectory(prec)
    for lb, lq in zip(base, quant):
        assert abs(lb - lq) <= VOCAB_TOL[prec][0], (prec, base, quant)


@pytest.mark.parametrize("zero_stage", [1, 3])
def test_vocab_epilogue_zero_stage_golden(zero_stage):
    """The composition cells vocab_parallel × zero_stage ∈ {1, 3} ×
    int8 (stage 3 on the model-sharded table degrades to state sharding
    while the non-tp stage leaves gather quantized)."""
    base = _lm_trajectory(None, zero_stage=zero_stage)
    quant = _lm_trajectory("int8", zero_stage=zero_stage)
    tol = max(VOCAB_TOL["int8"][0],
              ZERO3_TOL[0] if zero_stage >= 3 else 0.0)
    for lb, lq in zip(base, quant):
        assert abs(lb - lq) <= tol, (zero_stage, base, quant)


# ---------------------------------------------------------------------- #
# IR: normalization, serialization, backward compat
# ---------------------------------------------------------------------- #
def test_normalize_precision_forms():
    assert normalize_precision(None) == {}
    assert normalize_precision("fp32") == {}
    assert normalize_precision("int8") == {
        b: "int8" for b in PRECISION_BOUNDARIES}
    assert normalize_precision({"tp_psum": "bf16", "grad": "fp32"}) == {
        "tp_psum": "bf16"}
    with pytest.raises(UnknownPrecisionError):
        normalize_precision("int4")
    with pytest.raises(UnknownPrecisionError):
        normalize_precision({"tp_psum": "fp8"})
    with pytest.raises(UnknownPrecisionError):
        normalize_precision({"activations": "int8"})
    with pytest.raises(UnknownPrecisionError):
        normalize_precision(["int8"])


def test_pre_pr8_strategy_json_roundtrips_and_lowers_fp32():
    """A strategy JSON written before the precision fields existed (no
    'precision' keys anywhere) deserializes to the empty policy,
    re-serializes with the canonical empty dict, and lowers to the
    bit-exact fp32 program."""
    base_losses, base_params, strategy = _mlp_trajectory(1, 1, None)
    d = json.loads(strategy.to_json())
    # strip every PR-8 field — the on-disk shape of a pre-PR-8 strategy
    d["graph_config"].pop("precision", None)
    for nc in d["node_configs"]:
        if nc.get("partitioner"):
            nc["partitioner"].pop("precision", None)
    legacy_json = json.dumps(d)
    loaded = Strategy.from_json(legacy_json)
    assert loaded.graph_config.precision == {}
    assert json.loads(loaded.to_json())["graph_config"]["precision"] == {}
    losses, params, _ = _mlp_trajectory(1, 1, None,
                                        strategy_json=legacy_json)
    assert losses == base_losses
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), params, base_params)


def test_hand_edited_unknown_precision_rejected_by_name():
    _, _, strategy = _mlp_trajectory(1, 1, None)
    d = json.loads(strategy.to_json())
    d["graph_config"]["precision"] = {"tp_psum": "int4"}
    with pytest.raises(UnknownPrecisionError):
        Strategy.from_json(json.dumps(d))
    d["graph_config"]["precision"] = {"wormhole": "int8"}
    with pytest.raises(UnknownPrecisionError):
        Strategy.from_json(json.dumps(d))
    d["graph_config"]["precision"] = {}
    for nc in d["node_configs"]:
        if nc.get("partitioner"):
            nc["partitioner"]["precision"] = "fp8"
            break
    with pytest.raises(UnknownPrecisionError):
        Strategy.from_json(json.dumps(d))


def test_policy_roundtrips_through_json():
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t = _mlp_trainable()
    rs = ResourceSpec(SPEC_3D)
    s = Pipeline(num_microbatches=2, virtual_stages=2, tensor_parallel=2,
                 collective_precision={"tp_psum": "int8",
                                       "grad": "bf16"}).build(t, rs)
    back = Strategy.from_json(s.to_json())
    assert back.graph_config.precision == {"tp_psum": "int8",
                                           "grad": "bf16"}
    tp_parts = [nc.partitioner for nc in back.node_configs
                if nc.partitioner and nc.partitioner.spec
                and "model" in nc.partitioner.spec]
    assert tp_parts and all(p.precision == "int8" for p in tp_parts)


def test_grad_slot_conflicts_with_explicit_compressor():
    from autodist_tpu.strategy.parallel_builders import (ExpertParallel,
                                                         Pipeline,
                                                         SequenceParallel)

    for builder in (Pipeline, SequenceParallel, ExpertParallel):
        kw = {"num_microbatches": 2} if builder is Pipeline else {}
        with pytest.raises(ValueError, match="compressor"):
            builder(compressor="bf16_ef", collective_precision="int8",
                    **kw)


def test_vocab_stats_only_policy_does_not_narrow_tp_psums():
    """Slot hygiene (review regression): a vocab_stats-only policy
    records precision on the vocab-sharded SHARED table's partitioner;
    the lowering must not adopt that record into the tp_psum slot —
    the Megatron psums the user left at fp32 stay fp32."""
    import optax as _optax
    from jax.sharding import Mesh

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.parallel.pipeline import lower_pipeline_ir
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    t = make_pipeline_lm_trainable(cfg, _optax.sgd(0.05),
                                   jax.random.PRNGKey(0))
    s = Pipeline(num_microbatches=2, tensor_parallel=2,
                 vocab_parallel=True,
                 collective_precision={"vocab_stats": "int8"}).build(
                     t, ResourceSpec(SPEC_3D))
    # per-variable records land on the right variables only
    for nc in s.node_configs:
        part = nc.partitioner
        if part is None:
            continue
        if nc.var_name.startswith("shared/") and part.spec \
                and "model" in part.spec:
            assert part.precision == "int8", nc.var_name
        else:
            assert part.precision is None, nc.var_name
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    lowered = lower_pipeline_ir(t, s, mesh)   # jits untraced: cheap
    assert lowered.precision == {"vocab_stats": "int8"}

    # ...and a hand-edited strategy carrying ONLY the per-variable
    # records still resolves each into its own slot.
    s.graph_config.precision = {}
    lowered2 = lower_pipeline_ir(t, s, mesh)
    assert lowered2.precision == {"vocab_stats": "int8"}


def test_sequence_lowering_emits_precision_gauges(tmp_path):
    """The replicated-SPMD builder (sequence/expert lowerings) emits
    the same per-boundary gauges the pipeline does — the --check gate
    covers every lowering family (review regression)."""
    import optax as _optax
    from jax.sharding import Mesh

    from autodist_tpu import Trainable, telemetry
    from autodist_tpu.parallel.sequence import lower_sequence_ir
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.parallel_builders import SequenceParallel

    params = {"w": jnp.zeros((16, 4), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    t = Trainable.from_loss_fn(loss_fn, params, _optax.sgd(0.1))
    spec = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 2, "seq": 4}}
    s = SequenceParallel(collective_precision="int8").build(
        t, ResourceSpec(spec))
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path / "tel"))
    try:
        lower_sequence_ir(t, s, mesh)
        assert telemetry.get().gauge("precision/grad_bits").value == 8
        assert telemetry.get().gauge(
            "precision/zero3_gather_bits").value == 8
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------- #
# Cost model: election pinned both directions; calibration merge
# ---------------------------------------------------------------------- #
def _lm_cost_fixture():
    import optax as _optax

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = TransformerConfig(vocab_size=512, hidden_size=64, num_layers=2,
                            num_heads=2, mlp_dim=128, max_len=16,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    t = make_pipeline_lm_trainable(cfg, _optax.sgd(0.1),
                                   jax.random.PRNGKey(0))
    t.tokens_per_step = 32 * 16
    rs = ResourceSpec(SPEC_3D)
    fp32 = Pipeline(num_microbatches=2, tensor_parallel=2).build(t, rs)
    quant = Pipeline(num_microbatches=2, tensor_parallel=2,
                     collective_precision="int8").build(t, rs)
    return t, rs, fp32, quant


def test_quantized_candidate_wins_exactly_when_comm_bound():
    from autodist_tpu.simulator.cost_model import CostModel

    t, rs, fp32, quant = _lm_cost_fixture()
    # comm-bound link: bytes dominate, q/dq is noise -> quantized wins
    cm = CostModel(rs, link_profile={"ici_gbps": 0.001})
    c_f, c_q = cm.strategy_cost(t, fp32), cm.strategy_cost(t, quant)
    assert c_q.score < c_f.score
    assert c_q.wire_bytes_saved > 0
    assert c_q.comm_bytes < c_f.comm_bytes
    assert c_q.quant_dq_time_s > 0
    assert c_f.wire_bytes_saved == 0
    # compute-bound: infinite wire, calibrated q/dq cost -> fp32 wins
    cm2 = CostModel(rs, link_profile={"ici_gbps": 1e6},
                    quant_profile={"int8_s_per_elem": 1e-3})
    assert cm2.strategy_cost(t, fp32).score \
        < cm2.strategy_cost(t, quant).score


def test_auto_strategy_zoo_carries_quantized_candidates():
    from autodist_tpu.simulator.auto_strategy import default_candidates
    from autodist_tpu.strategy.parallel_builders import Pipeline

    quantized = [b for b in default_candidates()
                 if isinstance(b, Pipeline) and b.precision]
    assert quantized, "no quantized-collectives candidate in the zoo"
    assert any(b.precision.get("tp_psum") == "int8" for b in quantized)


def test_quant_calibration_section_merges(tmp_path, monkeypatch):
    from autodist_tpu.simulator import cost_model as cm

    path = tmp_path / "measured.json"
    path.write_text(json.dumps(
        {"meta": {"backend": "v5e"},
         "compressor_factor": {},
         "quant": {"int8_s_per_elem": 3.25e-9}}))
    monkeypatch.setitem(cm.QUANT_PROFILE, "int8_s_per_elem", 1e-10)
    cm.load_calibration(str(path))
    assert cm.QUANT_PROFILE["int8_s_per_elem"] == 3.25e-9
    # the model instance picks it up
    from autodist_tpu.resource import ResourceSpec
    model = cm.CostModel(ResourceSpec(SPEC_3D))
    assert model.quant_profile["int8_s_per_elem"] == 3.25e-9


def test_repo_calibration_quant_defaults_match_in_code_table():
    import os

    from autodist_tpu.simulator import cost_model as cm

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    with open(os.path.join(repo, "calibration.json")) as f:
        data = json.load(f)
    assert data["quant"] == {
        k: cm.QUANT_PROFILE[k]
        for k in ("bf16_s_per_elem", "int8_s_per_elem")}


# ---------------------------------------------------------------------- #
# Telemetry: the per-boundary gauge schema gate
# ---------------------------------------------------------------------- #
def _write_run(tmp_path, gauges, declared):
    run = tmp_path / "run"
    run.mkdir(parents=True)
    lines = [json.dumps({"kind": "gauge", "name": n, "value": v})
             for n, v in gauges.items()]
    (run / "metrics.jsonl").write_text("\n".join(lines) + "\n")
    (run / "manifest.json").write_text(json.dumps(
        {"kind": "manifest", "provenance": {},
         "run": {"collective_precision": declared}}))
    return str(run)


def test_report_check_gates_precision_gauges(tmp_path):
    from tools.telemetry_report import check_schema

    declared = {"tp_psum": "int8", "vocab_stats": "bf16"}
    ok = _write_run(tmp_path, {"precision/tp_psum_bits": 8,
                               "precision/vocab_stats_bits": 16},
                    declared)
    assert check_schema(ok) == []
    missing = _write_run(tmp_path / "m", {"precision/tp_psum_bits": 8},
                         declared)
    problems = check_schema(missing)
    assert any("vocab_stats" in p for p in problems)
    wrong = _write_run(tmp_path / "w", {"precision/tp_psum_bits": 16,
                                        "precision/vocab_stats_bits": 16},
                       declared)
    problems = check_schema(wrong)
    assert any("tp_psum" in p and "disagrees" in p for p in problems)
    bad_bits = _write_run(tmp_path / "b", {"precision/tp_psum_bits": 7,
                                           "precision/vocab_stats_bits": 16},
                          declared)
    assert any("wire width" in p for p in check_schema(bad_bits))


def test_lowering_emits_precision_gauges(tmp_path):
    """Lowering a bf16-policy pipeline strategy leaves the per-boundary
    gauges in the registry — the signal --check gates on."""
    from jax.sharding import Mesh

    from autodist_tpu import telemetry
    from autodist_tpu.parallel.pipeline import lower_pipeline_ir
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t = _mlp_trainable()
    strategy = Pipeline(num_microbatches=2, virtual_stages=2,
                        tensor_parallel=2,
                        collective_precision="bf16").build(
                            t, ResourceSpec(SPEC_3D))
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path / "tel"))
    try:
        lower_pipeline_ir(t, strategy, mesh)  # jits stay untraced: cheap
        assert telemetry.get().gauge("precision/tp_psum_bits").value == 16
        assert telemetry.get().gauge("precision/grad_bits").value == 16
        assert telemetry.get().gauge(
            "precision/zero3_gather_bits").value == 16
    finally:
        telemetry.reset()


def test_drift_report_breaks_out_wire_bytes_saved():
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.telemetry.drift import drift_report

    t, rs, _, quant = _lm_cost_fixture()
    cm = CostModel(rs)
    report = drift_report(quant, cm, {"step": {"p50_ms": 5.0}},
                          trainable=t)
    assert report["predicted"]["wire_bytes_saved"] > 0
    assert report["predicted"]["quant_dq_time_s"] > 0
