"""The shared quantize/dequantize layer (kernel/quantize.py): ONE
implementation of the int8 pack/unpack + error-feedback arithmetic used
by both the dp-grad compressors and the per-boundary precision policy.

Edge cases pinned directly (the PR 8 satellite): the all-zero block, the
single-element tensor, and non-divisible lanes through the padded
decomposed pair — each of which a naive scale/round would get wrong
(divide-by-zero, degenerate max, mis-sliced padding).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.kernel import quantize as qz


def test_quantize_int8_roundtrip_error_bounded():
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(257).astype(np.float32) * 3.0)
    q, scale = qz.quantize_int8(x)
    assert q.dtype == jnp.int8
    deq = qz.dequantize_int8(q, scale)
    # symmetric rounding: error per element <= scale/2
    assert float(jnp.max(jnp.abs(deq - x))) <= float(scale) / 2 + 1e-7


def test_all_zero_block_quantizes_to_exact_zeros():
    x = jnp.zeros(33, jnp.float32)
    q, scale = qz.quantize_int8(x)
    assert float(scale) > 0.0          # floored, not a divide-by-zero
    np.testing.assert_array_equal(np.asarray(q), 0)
    np.testing.assert_array_equal(np.asarray(qz.dequantize_int8(q, scale)),
                                  0.0)


def test_single_element_block():
    for v in (0.0, -3.25, 1e-10, 1e20):
        x = jnp.asarray([v], jnp.float32)
        q, scale = qz.quantize_int8(x)
        deq = qz.dequantize_int8(q, scale)
        if v == 0.0:
            assert float(deq[0]) == 0.0
        else:
            # a single element is its own abs-max: q = ±127 exactly,
            # so the roundtrip is exact up to fp rounding
            assert abs(int(np.asarray(q)[0])) == 127
            np.testing.assert_allclose(float(deq[0]), v, rtol=1e-5)


def test_error_feedback_identities():
    r = np.random.RandomState(1)
    g = jnp.asarray(r.randn(64).astype(np.float32))
    res = jnp.asarray(r.randn(64).astype(np.float32) * 0.01)
    corrected = qz.ef_correct(g, res)
    np.testing.assert_allclose(np.asarray(corrected),
                               np.asarray(g) + np.asarray(res), rtol=1e-6)
    q, scale = qz.quantize_int8(corrected)
    new_res = qz.ef_residual(corrected, qz.dequantize_int8(q, scale))
    # the residual IS what the wire lost
    np.testing.assert_allclose(
        np.asarray(new_res) + np.asarray(qz.dequantize_int8(q, scale)),
        np.asarray(corrected), rtol=1e-6)


def test_check_precision_rejects_unknown_values():
    assert qz.check_precision(None) == "fp32"
    assert qz.check_precision("bf16") == "bf16"
    with pytest.raises(qz.UnknownPrecisionError):
        qz.check_precision("int4")
    with pytest.raises(qz.UnknownPrecisionError):
        qz.check_precision("fp16", where="tp_psum")


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _shard_map(fn, mesh, n_out=1):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))


def test_quantized_psum_matches_psum_within_scale():
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(4, 37).astype(np.float32))
    mesh = _mesh()
    exact = _shard_map(lambda v: jax.lax.psum(v, "data"), mesh)(x)
    # 8 replicated summands of ~N(0,1): bf16's ~0.4% relative rounding
    # and int8's scale/2 per-summand rounding both bound well under
    # 0.25 absolute on a sum of magnitude ~8.
    for prec, tol in (("fp32", 0.0), ("bf16", 0.25), ("int8", 0.25)):
        out = _shard_map(
            lambda v, p=prec: qz.quantized_psum(v, "data", p), mesh)(x)
        err = float(jnp.max(jnp.abs(out - exact)))
        if prec == "fp32":
            assert err == 0.0
        else:
            assert err <= tol, (prec, err)


def test_quantized_all_gather_true_int8_wire_nondivisible_lanes():
    """The gather wire: 8 devices each contribute a 13-element shard
    (13 ∤ 8 lanes of anything — the padding/scale bookkeeping must not
    assume divisibility); per-shard scales dequantize independently."""
    r = np.random.RandomState(3)
    mesh = _mesh()
    shard = jnp.asarray(r.randn(13).astype(np.float32))

    def gathered(v, prec):
        return qz.quantized_all_gather_flat(v, "data", prec)

    exact = _shard_map(lambda v: gathered(v, "fp32"), mesh)(shard)
    for prec, tol in (("bf16", 0.02), ("int8", 0.02)):
        out = _shard_map(lambda v, p=prec: gathered(v, p), mesh)(shard)
        assert out.shape == exact.shape == (8 * 13,)
        assert float(jnp.max(jnp.abs(out - exact))) <= tol, prec


def test_quantized_psum_scatter_matches_reduce_scatter():
    r = np.random.RandomState(4)
    mesh = _mesh()
    flat = jnp.asarray(r.randn(40).astype(np.float32))  # 40 = 8 * 5
    exact = _shard_map(
        lambda v: qz.quantized_psum_scatter_flat(v, "data", "fp32"),
        mesh)(flat)
    for prec, tol in (("bf16", 0.2), ("int8", 0.3)):
        out = _shard_map(
            lambda v, p=prec: qz.quantized_psum_scatter_flat(v, "data", p),
            mesh)(flat)
        assert out.shape == exact.shape
        assert float(jnp.max(jnp.abs(out - exact))) <= tol, prec


def test_compressors_use_shared_helpers():
    """The dedup satellite's wiring check: the ring compressor's pack is
    literally the shared module's, and the EF compressors route through
    ef_correct/ef_residual (one implementation, two paths)."""
    from autodist_tpu.kernel.compressor import Int8RingCompressor

    assert Int8RingCompressor._quant is qz.quantize_int8
