"""Cross-process fleet goldens: the Router over real workers (ISSUE 17).

The bar: a request decodes the exact same token stream whether its
engine lives in this process or behind :class:`ProcessFleet`'s RPC
plane in a spawned worker — and a worker SIGKILLed mid-stream is
declared dead by the heartbeat sweep, replaced under the replacement
budget, and its in-flight requests re-dispatched to the same tokens,
with every worker pool settling to zero block residency.
"""
import json
import os
import time

import pytest

from autodist_tpu import telemetry
from autodist_tpu.serving import (ContinuousBatcher, FleetConfig,
                                  ProcessFleet, Router,
                                  tiny_engine_factory)

PROMPTS = [[1, 2, 3], [4, 5], [6], [7, 8, 9, 10]]
MAX_NEW = 6
FACTORY = "autodist_tpu.serving.remote:tiny_engine_factory"


@pytest.fixture(scope="module")
def golden():
    """Run-alone golden on the SAME factory the workers import."""
    out = {}
    b = ContinuousBatcher(tiny_engine_factory())
    for i, prompt in enumerate(PROMPTS):
        rid = b.submit(prompt, max_new_tokens=MAX_NEW)
        out[i] = b.run()[rid].tokens
    return out


@pytest.fixture()
def clean_env(monkeypatch):
    # A leaked worker identity would make THIS process think it is a
    # replica; a leaked service address would point the fleet at a
    # dead server from an earlier test.
    for var in ("AUTODIST_TPU_WORKER_REPLICA", "AUTODIST_TPU_FAULT_PLAN",
                "AUTODIST_TPU_COORD_SERVICE"):
        monkeypatch.delenv(var, raising=False)


def make_fleet(**overrides):
    kwargs = dict(replicas=2, heartbeat_interval_s=0.1,
                  heartbeat_timeout_s=2.0,
                  heartbeat_startup_grace_s=30.0)
    kwargs.update(overrides)
    return ProcessFleet({"factory": FACTORY},
                        config=FleetConfig(**kwargs))


def settle_zero_residency(fleet):
    acc = fleet.block_accounting(settle_s=5.0)
    for name, (free, used, total) in acc.items():
        assert used == 0 and free == total, (name, acc)


@pytest.mark.slow
def test_routed_across_worker_processes_matches_run_alone(clean_env,
                                                          golden):
    with make_fleet() as fleet:
        assert len(fleet.live) == 2
        assert all(r.handle.proc.pid != os.getpid()
                   for r in fleet.live)
        router = Router(fleet)
        rids = [router.submit(p, max_new_tokens=MAX_NEW)
                for p in PROMPTS]
        done = router.run()
        for i, rid in enumerate(rids):
            assert done[rid].tokens == golden[i], (i, done[rid])
        # queue-depth routing spread work across both workers
        assert {done[rid].replica for rid in rids} \
            == {"replica-0", "replica-1"}
        settle_zero_residency(fleet)


@pytest.mark.slow
def test_worker_sigkill_mid_stream_fails_over_and_replaces(clean_env,
                                                           golden):
    with make_fleet(max_replacements=1) as fleet:
        router = Router(fleet)
        rids = [router.submit(p, max_new_tokens=MAX_NEW)
                for p in PROMPTS]
        router.step()   # requests dispatched, streams open
        fleet.inject("replica-0", "crash")
        done = router.run()
        for i, rid in enumerate(rids):
            assert done[rid].tokens == golden[i], (i, done[rid])
        # the dead worker was replaced by a fresh incarnation
        names = {(r.name, r.incarnation) for r in fleet.live}
        assert ("replica-0", 1) in names, names
        assert ("replica-1", 0) in names, names
        settle_zero_residency(fleet)


@pytest.mark.slow
def test_sigkill_run_stitches_one_trace_across_processes(clean_env,
                                                         golden,
                                                         tmp_path):
    """The distributed-tracing acceptance path (ISSUE 19): a 2-replica
    ProcessFleet run with a mid-stream SIGKILL stitches every process's
    telemetry shard into ONE chrome trace — spans from >= 2 real pids,
    the fault visible, the failover re-dispatch visible, and every
    completion's trace id resolvable to stitched events — while the
    token streams still match the run-alone golden."""
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path))
    try:
        fleet = ProcessFleet(
            {"factory": FACTORY},
            config=FleetConfig(replicas=2, heartbeat_interval_s=0.1,
                               heartbeat_timeout_s=2.0,
                               heartbeat_startup_grace_s=30.0,
                               max_replacements=1),
            telemetry_dir=str(tmp_path))
        with fleet:
            router = Router(fleet)
            rids = [router.submit(p, max_new_tokens=MAX_NEW)
                    for p in PROMPTS]
            router.step()
            fleet.inject("replica-0", "crash")
            done = router.run()
            for i, rid in enumerate(rids):
                assert done[rid].tokens == golden[i], (i, done[rid])
            assert all(done[rid].trace_id for rid in rids)
            telemetry.flush()
        # close() waited for the graceful stop-op exits: every
        # surviving worker's shard is on disk before the stitch.
        trace = telemetry.stitch_trace(str(tmp_path))
        pids = trace["stitched"]["pids"]
        assert len([p for p in pids if p > 0]) >= 2, trace["stitched"]
        names = [ev["name"] for ev in trace["traceEvents"]]
        # a chief-side SIGKILL records detection + replacement (the
        # "injected" phase belongs to the chaos injector's records)
        assert "fault/detected" in names, sorted(set(names))
        assert "fault/recovered" in names, sorted(set(names))
        assert "dispatch/failover" in names, sorted(set(names))
        for rid in rids:
            tl = telemetry.request_timeline(trace, done[rid].trace_id)
            assert tl, (rid, done[rid].trace_id)
        # the stitched artifact round-trips: on-disk trace.json IS the
        # stitched trace and the schema/causal gates stay green
        with open(tmp_path / "trace.json") as f:
            assert json.load(f)["stitched"] == trace["stitched"]
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import telemetry_report as tr
        assert tr.check_schema(str(tmp_path)) == []
    finally:
        telemetry.reset()


@pytest.mark.slow
def test_fleet_close_is_idempotent_and_restores_env(clean_env):
    fleet = make_fleet(replicas=1)
    addr = os.environ.get("AUTODIST_TPU_COORD_SERVICE")
    assert addr  # the fleet published its coordination service
    fleet.close()
    fleet.close()
    assert os.environ.get("AUTODIST_TPU_COORD_SERVICE") is None
    # the worker honors the shutdown op on its own schedule
    deadline = time.monotonic() + 15.0
    while any(r.handle.running for r in fleet.replicas):
        assert time.monotonic() < deadline, \
            "worker outlived the fleet teardown"
        time.sleep(0.05)
