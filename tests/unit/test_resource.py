"""Resource model tests (≙ reference ``test_resource_spec.py`` /
``test_device_spec.py``: YAML parsing, defaults, validation)."""
import jax
import pytest

from autodist_tpu import ResourceSpec
from autodist_tpu import const


def test_default_spec_uses_all_devices():
    rs = ResourceSpec({})
    assert rs.num_devices() == 8
    assert rs.resolved_mesh_shape() == {const.DATA_AXIS: 8}
    mesh = rs.make_mesh()
    assert mesh.shape[const.DATA_AXIS] == 8


def test_explicit_mesh_shape():
    rs = ResourceSpec({"mesh": {"data": 4, "model": 2}})
    assert rs.resolved_mesh_shape() == {"data": 4, "model": 2}
    mesh = rs.make_mesh()
    assert mesh.shape == {"data": 4, "model": 2}


def test_wildcard_axis():
    rs = ResourceSpec({"mesh": {"data": -1, "model": 2}})
    assert rs.resolved_mesh_shape() == {"data": 4, "model": 2}


def test_num_devices_subset():
    rs = ResourceSpec({"topology": {"num_devices": 4}})
    assert rs.num_devices() == 4
    assert rs.resolved_mesh_shape() == {"data": 4}


def test_mismatched_mesh_raises():
    with pytest.raises(ValueError):
        ResourceSpec({"mesh": {"data": 3}}).resolved_mesh_shape()


def test_unknown_axis_raises():
    with pytest.raises(ValueError):
        ResourceSpec({"mesh": {"bogus": 8}})


def test_too_many_devices_raises():
    with pytest.raises(ValueError):
        ResourceSpec({"topology": {"num_devices": 64}}).devices()


def test_device_order_deterministic():
    # ≙ reference sorted node list (cluster.py:78-81)
    a = [d.id for d in ResourceSpec({}).devices()]
    b = [d.id for d in ResourceSpec({}).devices()]
    assert a == b == sorted(a)


def test_yaml_roundtrip(tmp_path):
    p = tmp_path / "spec.yml"
    p.write_text("topology:\n  platform: cpu\nmesh:\n  data: 8\n")
    rs = ResourceSpec(str(p))
    assert rs.platform == "cpu"
    assert rs.resolved_mesh_shape() == {"data": 8}


def test_chip_spec_lookup():
    rs = ResourceSpec({"topology": {"generation": "v5e"}})
    assert rs.chip.name == "v5e"
    assert rs.chip.peak_bf16_tflops > 0


def test_reference_style_nodes_spec_rejected():
    """Deliberate exclusion (docs/usage/migration.md): reference SSH GPU
    inventories are not a TPU topology; heterogeneous ones name the
    exclusion explicitly."""
    import pytest
    from autodist_tpu.resource import ResourceSpec

    hetero = {"nodes": [{"address": "a", "gpus": [0, 1]},
                        {"address": "b", "gpus": [0]}]}
    with pytest.raises(ValueError, match="heterogeneous replica sets"):
        ResourceSpec(hetero)

    homo = {"nodes": [{"address": "a", "gpus": [0, 1]},
                      {"address": "b", "gpus": [0, 1]}]}
    with pytest.raises(ValueError, match="not a TPU topology"):
        ResourceSpec(homo)


def test_local_proxy_variable_warns_at_lowering(caplog):
    """A no-op knob the user explicitly set must say so (reference
    ProxyVariable has no TPU analog: params re-gather every step)."""
    import logging as _logging

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, PS, Trainable

    t = Trainable.from_loss_fn(
        lambda p, b: jnp.mean((b["x"] @ p["w"]) ** 2),
        {"w": jnp.ones((4, 2))}, optax.sgd(0.1))
    ad = AutoDist({"topology": {"platform": "cpu", "num_devices": 8}},
                  PS(local_proxy_variable=True))
    from autodist_tpu.utils.logging import get_logger
    logger = get_logger()  # propagate=False: attach the capture handler
    logger.addHandler(caplog.handler)
    try:
        ad.build(t)
    finally:
        logger.removeHandler(caplog.handler)
    assert any("local_proxy_variable" in r.message for r in caplog.records)
