"""RetryPolicy tier-1 pins: deterministic under a fixed seed, gives up
at the deadline, never fires on success — and bench.py's UNAVAILABLE
backoff is the same one implementation."""
import pytest

from autodist_tpu.runtime.retry import (RetryError, RetryPolicy,
                                        backoff_delay)


def test_backoff_delay_capped_exponential():
    assert [backoff_delay(a, 5.0, 60.0) for a in range(1, 6)] == \
        [5.0, 10.0, 20.0, 40.0, 60.0]


def test_bench_backoff_is_the_shared_implementation():
    import bench

    assert [bench._backoff_delay(a) for a in range(1, 6)] == \
        [backoff_delay(a, 5.0, 60.0) for a in range(1, 6)]


def test_delays_deterministic_under_fixed_seed():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, cap_delay_s=2.0,
                    seed=42)
    assert p.delays() == p.delays()
    assert len(p.delays()) == 4
    # a different seed gives a different jitter draw
    q = RetryPolicy(max_attempts=5, base_delay_s=0.1, cap_delay_s=2.0,
                    seed=43)
    assert p.delays() != q.delays()
    # jitter stays within +/- the configured fraction of the base curve
    for a, d in enumerate(p.delays(), start=1):
        base = p.delay_s(a)
        assert base * 0.5 <= d <= base * 1.5


def test_never_fires_on_success():
    slept = []
    p = RetryPolicy(max_attempts=5, base_delay_s=1.0, seed=0)
    calls = []

    def ok():
        calls.append(1)
        return 99

    assert p.call(ok, sleep=slept.append) == 99
    assert len(calls) == 1 and slept == []


def test_retries_then_succeeds_with_seeded_schedule():
    slept = []
    p = RetryPolicy(max_attempts=4, base_delay_s=0.1, cap_delay_s=1.0,
                    seed=7)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return "done"

    assert p.call(flaky, sleep=slept.append) == "done"
    assert state["n"] == 3
    assert slept == p.delays()[:2]   # the exact seeded schedule


def test_gives_up_after_attempt_budget():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(RetryError) as ei:
        p.call(always, sleep=lambda s: None)
    assert len(calls) == 3
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, OSError)


def test_gives_up_at_the_deadline():
    # fake clock: each attempt "takes" 10s; deadline 15s -> the second
    # retry would land past the deadline and must not run.
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(s):
        t["now"] += s

    calls = []

    def always():
        calls.append(1)
        t["now"] += 10.0
        raise OSError("down")

    p = RetryPolicy(max_attempts=10, base_delay_s=1.0, jitter=0.0,
                    deadline_s=15.0)
    with pytest.raises(RetryError, match="deadline"):
        p.call(always, sleep=sleep, clock=clock)
    assert len(calls) == 2   # attempt 1 (10s) + retry (11s) > 15s stops


def test_non_retryable_propagates_unwrapped():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.0,
                    retryable=(OSError,))
    with pytest.raises(ValueError, match="bug"):
        p.call(lambda: (_ for _ in ()).throw(ValueError("bug")),
               sleep=lambda s: None)


def test_predicate_classification():
    p = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                    retryable=lambda e: "retry-me" in str(e))
    with pytest.raises(RetryError):
        p.call(lambda: (_ for _ in ()).throw(OSError("retry-me")),
               sleep=lambda s: None)
    with pytest.raises(OSError, match="not-this"):
        p.call(lambda: (_ for _ in ()).throw(OSError("not-this")),
               sleep=lambda s: None)


def test_max_total_delay_is_the_lint_bound():
    p = RetryPolicy(max_attempts=3, base_delay_s=1.0, cap_delay_s=10.0,
                    jitter=0.5)
    # retries after attempts 1 and 2: (1 + 2) * 1.5 worst case
    assert p.max_total_delay_s() == pytest.approx(4.5)
