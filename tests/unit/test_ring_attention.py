"""Ring attention correctness: must match single-device full attention
exactly (same math, different schedule), forward and backward."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.parallel.ring_attention import (ring_self_attention,
                                                  sequence_sharded_attention)


pytestmark = pytest.mark.slow

def reference_attention(q, k, v, causal=False):
    D = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
    if causal:
        L = q.shape[1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def make_qkv(seed=0, B=2, L=32, H=4, D=16):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(B, L, H, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_matches_reference(causal):
    q, k, v = make_qkv()
    mesh = jax.make_mesh((8,), ("seq",))
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_gradients_match(causal):
    q, k, v = make_qkv(seed=1, L=16)
    mesh = jax.make_mesh((4,), ("seq",))

    def ring_loss(q, k, v):
        return (sequence_sharded_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_in_transformer_config():
    """attention_fn plug-in point: TransformerLM forward under a seq mesh."""
    from autodist_tpu.parallel.ring_attention import make_ring_attention_fn
    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 dot_product_attention)
    # Smoke check that the adapter signature matches the plug-in contract.
    mesh = jax.make_mesh((4,), ("seq",))
    fn = make_ring_attention_fn(causal=True)
    assert callable(fn)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_flash_matches_reference(causal):
    """Pallas-per-chunk ring (interpret mode on the CPU mesh) must match
    single-device softmax attention."""
    q, k, v = make_qkv(seed=2)
    mesh = jax.make_mesh((4,), ("seq",))
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                     flash=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_flash_gradients_match(causal):
    """The merge consumes each chunk's lse, so this exercises the flash
    kernel's lse-cotangent VJP path end to end."""
    q, k, v = make_qkv(seed=3, L=16)
    mesh = jax.make_mesh((4,), ("seq",))

    def ring_loss(q, k, v):
        return (sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                           flash=True) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_lse_cotangent_direct():
    """flash_attention_with_lse: a loss that reads *lse itself* must
    differentiate like the einsum logsumexp formulation."""
    from autodist_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = make_qkv(seed=4, B=1, L=16, H=2, D=8)

    def flash_loss(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v)
        return (out ** 2).sum() + (jnp.sin(lse)).sum()

    def ref_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
        lse = jnp.moveaxis(jax.nn.logsumexp(s, axis=-1), 1, 2)  # [B,L,H]
        return (out ** 2).sum() + (jnp.sin(lse)).sum()

    g_flash = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
