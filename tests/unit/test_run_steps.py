"""run_steps: k optimizer steps in one dispatch must be bit-equivalent
to k sequential step() calls (steps-per-loop is an execution detail, not
a semantics change)."""
import jax
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, PartitionedPS, ZeRO,
                          stack_steps as stack_batches)

from test_end_to_end import make_batch, make_trainable


@pytest.mark.parametrize("name,builder", [
    ("AllReduce", lambda: AllReduce(chunk_size=2)),
    ("PartitionedPS", lambda: PartitionedPS()),
    ("ZeRO2", lambda: ZeRO(stage=2)),
], ids=["AllReduce", "PartitionedPS", "ZeRO2"])
def test_run_steps_matches_sequential(name, builder):
    batches = [make_batch(s) for s in range(4)]
    rngs = jax.random.split(jax.random.PRNGKey(7), 4)

    seq = AutoDist({}, builder()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    for b, r in zip(batches, rngs):
        last = seq.step(b, rng=r)

    fused = AutoDist({}, builder()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    metrics = fused.run_steps(stack_batches(batches), rngs=rngs)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        fused.get_params(), seq.get_params())
    assert fused.step_count == seq.step_count == 4
    # metrics carry the per-step leading axis; the last slice is step()'s
    # fetch contract
    np.testing.assert_allclose(np.asarray(metrics["loss"])[-1],
                               np.asarray(last["loss"]), rtol=1e-6)
    assert np.asarray(metrics["loss"]).shape[0] == 4


def test_run_steps_then_step_interleave():
    """State handoff between fused and per-step dispatch is seamless."""
    batches = [make_batch(s) for s in range(3)]
    rngs = jax.random.split(jax.random.PRNGKey(3), 3)

    seq = AutoDist({}, AllReduce()).build(make_trainable())
    for b, r in zip(batches, rngs):
        seq.step(b, rng=r)

    mixed = AutoDist({}, AllReduce()).build(make_trainable())
    mixed.run_steps(stack_batches(batches[:2]), rngs=rngs[:2])
    mixed.step(batches[2], rng=rngs[2])

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        mixed.get_params(), seq.get_params())


def test_run_steps_gspmd_matches_sequential():
    """run_steps through the gspmd lowering (FSDP-sharded params on the
    data axis) — same bit-equivalence contract as the shard_map path."""
    import optax

    from autodist_tpu import FSDPSharded

    bs = [make_batch(s) for s in range(3)]
    rngs = jax.random.split(jax.random.PRNGKey(13), 3)

    seq = AutoDist({}, FSDPSharded()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    for b, r in zip(bs, rngs):
        seq.step(b, rng=r)

    fused = AutoDist({}, FSDPSharded()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    m = fused.run_steps(stack_batches(bs), rngs=rngs)
    assert np.asarray(m["loss"]).shape[0] == 3
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        fused.get_params(), seq.get_params())


def test_run_steps_sequence_parallel_matches_sequential():
    """run_steps through the SimpleLowered path (sequence-parallel
    lowering on a data x seq mesh) — same bit-equivalence contract."""
    from test_parallel_zero import (SEQ_SPEC, assert_trees_close,
                                    lm_batches, make_lm_trainable)

    bs = lm_batches(3)
    rngs = jax.random.split(jax.random.PRNGKey(11), 3)

    seq = AutoDist(SEQ_SPEC, "SequenceParallel").build(
        make_lm_trainable(sharded=True))
    for b, r in zip(bs, rngs):
        seq.step(b, rng=r)

    fused = AutoDist(SEQ_SPEC, "SequenceParallel").build(
        make_lm_trainable(sharded=True))
    m = fused.run_steps(stack_batches(bs), rngs=rngs)
    assert np.asarray(m["loss"]).shape[0] == 3
    assert_trees_close(fused.get_params(), seq.get_params(),
                       rtol=1e-6, atol=1e-7)


def test_run_steps_pipeline_matches_sequential():
    """run_steps through the pipeline lowering (data x pipe mesh)."""
    from test_parallel_ir import (PIPE_SPEC, make_pipeline_trainable,
                                  pipe_batches)

    bs = pipe_batches(3)
    rngs = jax.random.split(jax.random.PRNGKey(5), 3)

    seq = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2).build(
        make_pipeline_trainable())
    for b, r in zip(bs, rngs):
        seq.step(b, rng=r)

    fused = AutoDist(PIPE_SPEC, "Pipeline", num_microbatches=2).build(
        make_pipeline_trainable())
    m = fused.run_steps(stack_batches(bs), rngs=rngs)
    assert np.asarray(m["loss"]).shape[0] == 3
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        fused.get_params(), seq.get_params())


def test_run_steps_composes_with_checkpoint(tmp_path):
    """Save after a fused window, restore into a fresh runner, continue
    fused — bit-identical to an unbroken fused run (steps-per-loop is an
    execution detail to the checkpoint contract too)."""
    import optax

    from autodist_tpu.checkpoint.saver import Saver

    bs = [make_batch(s) for s in range(4)]
    rngs = jax.random.split(jax.random.PRNGKey(21), 4)

    unbroken = AutoDist({}, PartitionedPS()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    unbroken.run_steps(stack_batches(bs), rngs=rngs)

    first = AutoDist({}, PartitionedPS()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    first.run_steps(stack_batches(bs[:2]), rngs=rngs[:2])
    saver = Saver(str(tmp_path))
    saver.save(first)

    resumed = AutoDist({}, PartitionedPS()).build(
        make_trainable(optimizer=optax.adam(1e-2)))
    saver.restore(resumed)
    assert resumed.step_count == 2
    resumed.run_steps(stack_batches(bs[2:]), rngs=rngs[2:])

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        resumed.get_params(), unbroken.get_params())


def test_run_steps_ssp_fallback_honors_rngs():
    """Under an active SSP gate run_steps falls back to per-step
    dispatch; caller-supplied rngs must drive each step (an rng-dependent
    loss detects a fallback that silently substitutes self.rng)."""
    import os

    import jax.numpy as jnp
    import optax

    from autodist_tpu import PS, Trainable
    from autodist_tpu.runtime import coordination
    from autodist_tpu.runtime.coordination import CoordServer

    def make_noisy():
        params = {"w": jnp.ones((6, 3), jnp.float32)}

        def loss_fn(p, batch, rng):
            keep = jax.random.bernoulli(
                rng, 0.8, batch["x"].shape).astype(jnp.float32)
            pred = (batch["x"] * keep) @ p["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                      with_rng=True)

    rng_np = np.random.RandomState(0)
    bs = [{"x": rng_np.randn(16, 6).astype(np.float32),
           "y": rng_np.randn(16, 3).astype(np.float32)} for _ in range(3)]
    rngs = jax.random.split(jax.random.PRNGKey(9), 3)

    server = CoordServer()
    os.environ["AUTODIST_TPU_COORD_SERVICE"] = f"127.0.0.1:{server.port}"
    coordination.reset_service_client()
    try:
        ad = AutoDist({}, PS(sync=True, staleness=1))
        seq = ad.build(make_noisy(), ssp_worker="a", ssp_num_workers=1)
        assert seq._ssp is not None
        for b, r in zip(bs, rngs):
            seq.step(b, rng=r)

        fused = ad.build(make_noisy(), ssp_worker="b", ssp_num_workers=1)
        m = fused.run_steps(stack_batches(bs), rngs=rngs)
        assert np.asarray(m["loss"]).shape[0] == 3
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
            fused.get_params(), seq.get_params())
    finally:
        os.environ.pop("AUTODIST_TPU_COORD_SERVICE", None)
        coordination.reset_service_client()
        server.stop()


def test_run_steps_ragged_leading_dim_raises():
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    bad = {"x": np.zeros((2, 16, 6), np.float32),
           "y": np.zeros((3, 16, 3), np.float32)}
    with pytest.raises(ValueError, match="leading steps dimension"):
        runner.run_steps(bad)


def test_run_steps_scalar_leaf_raises():
    """Duplicate-feed scalars must arrive stacked [k] (one per step) —
    an unstacked scalar gets the contract error, not an IndexError."""
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    bad = {"s": np.float32(1.0),
           "x": np.zeros((2, 16, 6), np.float32)}
    with pytest.raises(ValueError, match="leading steps dimension"):
        runner.run_steps(bad)
