"""Runtime layer tests: coordinator process management (fail-fast
semantics ≙ reference coordinator watcher, ``coordinator.py:98-110``),
per-host feeding, profiling meters and stage dumps."""
import os
import subprocess
import sys
import time

import jax
import numpy as np
import optax
import pytest

from autodist_tpu.runtime.cluster import (Cluster, Coordinator,
                                          make_global_batch)
from autodist_tpu.utils.profiling import (StepTimer, dump_stages, mfu,
                                          transformer_train_flops_per_token)


def test_coordinator_success_join():
    c = Coordinator()
    c.launch("ok-1", [sys.executable, "-c", "print('hi')"])
    c.launch("ok-2", [sys.executable, "-c", "import time; time.sleep(0.2)"])
    c.join(timeout=30)


def test_coordinator_fail_fast_kills_siblings():
    c = Coordinator()
    slow = c.launch("slow", [sys.executable, "-c",
                             "import time; time.sleep(60)"])
    c.launch("bad", [sys.executable, "-c", "import sys; sys.exit(3)"])
    with pytest.raises(RuntimeError, match="bad.*3"):
        c.join(timeout=30)
    # the long-running sibling must have been terminated (fail-fast)
    deadline = time.time() + 10
    while slow.running and time.time() < deadline:
        time.sleep(0.1)
    assert not slow.running


def test_coordinator_timeout():
    c = Coordinator()
    c.launch("hang", [sys.executable, "-c", "import time; time.sleep(60)"])
    with pytest.raises(TimeoutError):
        c.join(timeout=1)


def test_cluster_launch_env_plane(tmp_path):
    """Workers get the role env vars (≙ AUTODIST_WORKER/STRATEGY_ID)."""
    out = tmp_path / "env.txt"
    script = tmp_path / "w.py"
    script.write_text(
        "import os\n"
        "open(%r, 'w').write(os.environ.get('AUTODIST_TPU_WORKER','') + '|' +\n"
        "    os.environ.get('AUTODIST_TPU_STRATEGY_ID','') + '|' +\n"
        "    os.environ.get('AUTODIST_TPU_PROCESS_ID',''))\n" % str(out))
    from autodist_tpu import ResourceSpec
    cluster = Cluster(ResourceSpec({}), hosts=["localhost"])
    cluster.launch_clients("strat-42", argv=[sys.executable, str(script)])
    cluster.join(timeout=30)
    assert out.read_text() == "localhost|strat-42|1"


def test_make_global_batch_single_host():
    mesh = jax.make_mesh((8,), ("data",))
    batch = {"x": np.arange(16.0).reshape(16, 1)}
    global_b = make_global_batch(batch, mesh)
    assert global_b["x"].shape == (16, 1)
    assert global_b["x"].sharding.spec == jax.sharding.PartitionSpec("data")


def test_step_timer_and_mfu():
    t = StepTimer(batch_size=64, warmup=1)
    for _ in range(4):
        with t:
            time.sleep(0.01)
    s = t.summary()
    assert s["steps"] == 3
    assert s["examples_per_sec"] > 0
    assert 0 < mfu(1000, transformer_train_flops_per_token(1_000_000),
                   1e15) < 1


def test_dump_stages(tmp_path):
    from autodist_tpu import AllReduce, AutoDist
    from tests.unit.test_end_to_end import make_batch, make_trainable

    trainable = make_trainable()
    ad = AutoDist({}, AllReduce())
    strategy = ad.build_or_load_strategy(trainable)
    lowered = ad.lower(trainable, strategy)
    runner_batch = jax.tree.map(lambda x: jax.numpy.asarray(x), make_batch())
    out = dump_stages(lowered, trainable, strategy, str(tmp_path),
                      example_batch=runner_batch)
    names = sorted(os.listdir(out))
    assert "0-strategy.json" in names
    assert "1-plan.txt" in names
    assert "2-step.hlo.txt" in names
    hlo = open(os.path.join(out, "2-step.hlo.txt")).read()
    assert "all-reduce" in hlo or "all_reduce" in hlo.replace("-", "_")


def test_eval_step_no_update():
    from autodist_tpu import AllReduce, AutoDist, PartitionedPS
    from autodist_tpu.strategy.gspmd_builders import Sharded
    from tests.unit.test_end_to_end import make_batch, make_trainable

    for builder in (AllReduce(), PartitionedPS(), Sharded()):
        runner = AutoDist({}, builder).build(make_trainable())
        before = runner.get_params()
        m = runner.eval_step(make_batch())
        assert np.isfinite(float(m["loss"]))
        after = runner.get_params()
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), before, after)
        # evaluate() over several batches
        agg = runner.evaluate([make_batch(s) for s in range(3)])
        assert "loss" in agg and np.isfinite(agg["loss"])


def test_memory_summary_shapes():
    from autodist_tpu.utils import profiling

    # CPU backend exposes no HBM stats -> {}.
    assert profiling.memory_summary() in ({},) or isinstance(
        profiling.memory_summary(), dict)

    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 500, "bytes_limit": 1000,
                    "peak_bytes_in_use": 800, "label": "x"}

    out = profiling.memory_summary(FakeDev())
    assert out["bytes_in_use"] == 500 and out["utilization"] == 0.5
    assert "label" not in out


def test_native_build_falls_back_to_user_cache(monkeypatch, tmp_path):
    """Read-only installs (system site-packages, container layers) build
    the native libraries in XDG_CACHE_HOME instead of next to the
    sources."""
    import os

    from autodist_tpu.runtime import nativelib as nl

    real_access = os.access
    monkeypatch.setattr(
        nl.os, "access",
        lambda p, m: False if p == nl.NATIVE_DIR else real_access(p, m))
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    nl._loaded.clear()
    lib = nl.load_native("libautodist_dataio.so", "dataio.cc")
    assert lib is not None
    cache = tmp_path / "autodist_tpu" / "native"
    assert (cache / "libautodist_dataio.so").exists()
    assert (cache / "dataio.cc").exists()   # sources copied for make
    nl._loaded.clear()                      # don't leak the cache CDLL


# --------------------------------------------------------------------------- #
# Chaos-hardened runtime: supervision, heartbeats, remote teardown,
# full-failure reporting (with supervision OFF, fail-fast is untouched —
# the tests above this line run the exact pre-supervision semantics).
# --------------------------------------------------------------------------- #
def _crash_once_script():
    """Exit 3 on the first incarnation, 0 after a supervised restart."""
    return [sys.executable, "-c",
            "import os, sys; "
            "sys.exit(0 if os.environ.get("
            "'AUTODIST_TPU_WORKER_INCARNATION') else 3)"]


def test_supervised_restart_within_budget():
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.cluster import Coordinator, SupervisionConfig
    from autodist_tpu.runtime.retry import RetryPolicy

    telemetry.reset()
    sup = SupervisionConfig(
        max_restarts=1,
        restart_backoff=RetryPolicy(max_attempts=2, base_delay_s=0.05,
                                    cap_delay_s=0.05, seed=0))
    c = Coordinator(supervision=sup)
    c.launch("w1", _crash_once_script())
    c.join(timeout=30)    # restart consumed the crash: join is clean
    assert c._restarts == {"w1": 1}
    assert telemetry.get().registry.counter(
        "runtime/worker_restarts").value == 1
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    assert any(r["phase"] == "recovered" and r["action"] == "restart"
               and r["target"] == "w1" for r in recs)


def test_supervised_escalation_hands_over_survivors():
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.cluster import Coordinator, SupervisionConfig

    telemetry.reset()
    seen = {}
    sup = SupervisionConfig(max_restarts=0, escalate=True, saver=object(),
                            on_escalate=lambda s: seen.update(
                                names=[w.name for w in s]))
    c = Coordinator(supervision=sup)
    c.launch("survivor", [sys.executable, "-c",
                          "import time; time.sleep(2)"])
    c.launch("doomed", [sys.executable, "-c", "import sys; sys.exit(9)"])
    deadline = time.time() + 10
    while not c.escalated and time.time() < deadline:
        time.sleep(0.05)
    assert c.escalated
    assert seen["names"] == ["survivor"]
    c.join(timeout=30)   # the escalated death is consumed, join is clean
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    assert any(r["phase"] == "escalated" and r["target"] == "doomed"
               for r in recs)


def test_supervision_off_keeps_fail_fast_teardown_records_nothing():
    """Both-ways pin: with supervision=None the fail-fast path emits no
    fault records and raises exactly as before."""
    from autodist_tpu import telemetry

    telemetry.reset()
    c = Coordinator()
    c.launch("bad", [sys.executable, "-c", "import sys; sys.exit(3)"])
    with pytest.raises(RuntimeError, match="bad.*3"):
        c.join(timeout=30)
    assert not [r for r in telemetry.get().step_records()
                if r.get("kind") == "fault"]


def test_join_reports_all_concurrent_failures():
    c = Coordinator(fail_fast=False)
    c.launch("bad-a", [sys.executable, "-c", "import sys; sys.exit(3)"])
    c.launch("bad-b", [sys.executable, "-c", "import sys; sys.exit(5)"])
    with pytest.raises(RuntimeError) as ei:
        c.join(timeout=30)
    msg = str(ei.value)
    assert "bad-a" in msg and "3" in msg
    assert "bad-b" in msg and "5" in msg


def test_join_timeout_lists_hung_and_crashed_workers():
    c = Coordinator(fail_fast=False)
    c.launch("crashed", [sys.executable, "-c", "import sys; sys.exit(7)"])
    c.launch("hung-a", [sys.executable, "-c", "import time; time.sleep(60)"])
    c.launch("hung-b", [sys.executable, "-c", "import time; time.sleep(60)"])
    time.sleep(1.0)   # let the crash land
    with pytest.raises(TimeoutError) as ei:
        c.join(timeout=2)
    msg = str(ei.value)
    assert "hung-a" in msg and "hung-b" in msg
    assert "crashed" in msg and "7" in msg


class _StallingClient:
    """Heartbeat source that beats a few times, then stalls (the
    SIGSTOPped-worker signature)."""

    def __init__(self, beats=5):
        self.n = 0
        self.beats = beats

    def counter_add(self, key, delta=0):
        self.n += 1
        return min(self.n, self.beats)


def test_heartbeat_monitor_declares_hung_worker_dead():
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.cluster import (Coordinator,
                                              HeartbeatMonitor,
                                              SupervisionConfig)

    telemetry.reset()
    sup = SupervisionConfig(max_restarts=0, escalate=True, saver=object())
    c = Coordinator(supervision=sup)
    c.launch("wedged", [sys.executable, "-c",
                        "import time; time.sleep(60)"])
    mon = HeartbeatMonitor(c, lambda: _StallingClient(),
                           interval_s=0.05, timeout_s=0.4,
                           startup_grace_s=0.4)
    mon.start()
    try:
        deadline = time.time() + 10
        while not c.escalated and time.time() < deadline:
            time.sleep(0.05)
        assert c.escalated, "hang was never detected/escalated"
    finally:
        mon.stop()
        c.terminate()
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    assert any(r["phase"] == "detected" and r["fault"] == "worker_hang"
               for r in recs)
    assert any(r["phase"] == "escalated" and r["fault"] == "worker_hang"
               for r in recs)
    assert telemetry.get().registry.counter(
        "runtime/workers_declared_dead").value == 1


_FAKE_SSH = """#!%(python)s
import os, subprocess, sys
args = sys.argv[1:]
while args and args[0].startswith("-"):
    args = args[2:]                      # drop "-o BatchMode=yes" pairs
host, rest = args[0], args[1:]
if rest == ["/bin/sh -s"]:
    # launch form: the "remote" worker runs DETACHED (own session), like
    # a real remote process — killing the local ssh client must not
    # reach it.
    proc = subprocess.Popen(["/bin/sh", "-s"], stdin=sys.stdin,
                            start_new_session=True)
    sys.exit(proc.wait())
# exec form (the teardown kill): run the command locally
sys.exit(subprocess.call(["/bin/sh", "-c", " ".join(rest)]))
"""


def test_remote_worker_teardown_kills_the_remote_process(tmp_path,
                                                         monkeypatch):
    """The satellite pin: terminate() on an ssh-launched worker must kill
    the REMOTE process (via the captured remote pid + a second ssh
    exec), not just the local ssh client.  The fake ssh shim runs the
    'remote' side as a detached local process group."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    ssh = bin_dir / "ssh"
    ssh.write_text(_FAKE_SSH % {"python": sys.executable})
    ssh.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    pidfile = tmp_path / "pid"
    c = Coordinator()
    h = c.launch(
        "remote-1",
        [sys.executable, "-c",
         f"import os, time; open({str(pidfile)!r}, 'w').write("
         "str(os.getpid())); time.sleep(60)"],
        host="fakehost", env={"SOME_SECRET": "s3cret"})
    deadline = time.time() + 15
    while (h.remote_pid is None or not pidfile.exists()) \
            and time.time() < deadline:
        time.sleep(0.05)
    assert h.remote_pid is not None, "remote pid never captured"
    worker_pid = int(pidfile.read_text())
    # exec in the bootstrap keeps the sh pid: the marker IS the worker
    assert h.remote_pid == worker_pid
    os.kill(worker_pid, 0)   # alive
    c.terminate()
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            os.kill(worker_pid, 0)
            time.sleep(0.05)
        except ProcessLookupError:
            break
    with pytest.raises(ProcessLookupError):
        os.kill(worker_pid, 0)   # the REMOTE side is dead, not orphaned


def test_local_cluster_launches_n_workers(tmp_path):
    from autodist_tpu.runtime.cluster import LocalCluster

    outs = [tmp_path / f"w{i}.txt" for i in (1, 2)]
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "pid = os.environ['AUTODIST_TPU_PROCESS_ID']\n"
        f"open(os.path.join({str(tmp_path)!r}, 'w%s.txt' % pid), "
        "'w').write(os.environ.get('AUTODIST_TPU_STRATEGY_ID', ''))\n")
    cluster = LocalCluster(2)
    try:
        cluster.launch_clients("strat-7",
                               argv=[sys.executable, str(script)])
        cluster.join(timeout=60)
    finally:
        cluster.terminate()
    assert [o.read_text() for o in outs] == ["strat-7", "strat-7"]
