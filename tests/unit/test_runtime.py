"""Runtime layer tests: coordinator process management (fail-fast
semantics ≙ reference coordinator watcher, ``coordinator.py:98-110``),
per-host feeding, profiling meters and stage dumps."""
import os
import subprocess
import sys
import time

import jax
import numpy as np
import optax
import pytest

from autodist_tpu.runtime.cluster import (Cluster, Coordinator,
                                          make_global_batch)
from autodist_tpu.utils.profiling import (StepTimer, dump_stages, mfu,
                                          transformer_train_flops_per_token)


def test_coordinator_success_join():
    c = Coordinator()
    c.launch("ok-1", [sys.executable, "-c", "print('hi')"])
    c.launch("ok-2", [sys.executable, "-c", "import time; time.sleep(0.2)"])
    c.join(timeout=30)


def test_coordinator_fail_fast_kills_siblings():
    c = Coordinator()
    slow = c.launch("slow", [sys.executable, "-c",
                             "import time; time.sleep(60)"])
    c.launch("bad", [sys.executable, "-c", "import sys; sys.exit(3)"])
    with pytest.raises(RuntimeError, match="bad.*3"):
        c.join(timeout=30)
    # the long-running sibling must have been terminated (fail-fast)
    deadline = time.time() + 10
    while slow.running and time.time() < deadline:
        time.sleep(0.1)
    assert not slow.running


def test_coordinator_timeout():
    c = Coordinator()
    c.launch("hang", [sys.executable, "-c", "import time; time.sleep(60)"])
    with pytest.raises(TimeoutError):
        c.join(timeout=1)


def test_cluster_launch_env_plane(tmp_path):
    """Workers get the role env vars (≙ AUTODIST_WORKER/STRATEGY_ID)."""
    out = tmp_path / "env.txt"
    script = tmp_path / "w.py"
    script.write_text(
        "import os\n"
        "open(%r, 'w').write(os.environ.get('AUTODIST_TPU_WORKER','') + '|' +\n"
        "    os.environ.get('AUTODIST_TPU_STRATEGY_ID','') + '|' +\n"
        "    os.environ.get('AUTODIST_TPU_PROCESS_ID',''))\n" % str(out))
    from autodist_tpu import ResourceSpec
    cluster = Cluster(ResourceSpec({}), hosts=["localhost"])
    cluster.launch_clients("strat-42", argv=[sys.executable, str(script)])
    cluster.join(timeout=30)
    assert out.read_text() == "localhost|strat-42|1"


def test_make_global_batch_single_host():
    mesh = jax.make_mesh((8,), ("data",))
    batch = {"x": np.arange(16.0).reshape(16, 1)}
    global_b = make_global_batch(batch, mesh)
    assert global_b["x"].shape == (16, 1)
    assert global_b["x"].sharding.spec == jax.sharding.PartitionSpec("data")


def test_step_timer_and_mfu():
    t = StepTimer(batch_size=64, warmup=1)
    for _ in range(4):
        with t:
            time.sleep(0.01)
    s = t.summary()
    assert s["steps"] == 3
    assert s["examples_per_sec"] > 0
    assert 0 < mfu(1000, transformer_train_flops_per_token(1_000_000),
                   1e15) < 1


def test_dump_stages(tmp_path):
    from autodist_tpu import AllReduce, AutoDist
    from tests.unit.test_end_to_end import make_batch, make_trainable

    trainable = make_trainable()
    ad = AutoDist({}, AllReduce())
    strategy = ad.build_or_load_strategy(trainable)
    lowered = ad.lower(trainable, strategy)
    runner_batch = jax.tree.map(lambda x: jax.numpy.asarray(x), make_batch())
    out = dump_stages(lowered, trainable, strategy, str(tmp_path),
                      example_batch=runner_batch)
    names = sorted(os.listdir(out))
    assert "0-strategy.json" in names
    assert "1-plan.txt" in names
    assert "2-step.hlo.txt" in names
    hlo = open(os.path.join(out, "2-step.hlo.txt")).read()
    assert "all-reduce" in hlo or "all_reduce" in hlo.replace("-", "_")


def test_eval_step_no_update():
    from autodist_tpu import AllReduce, AutoDist, PartitionedPS
    from autodist_tpu.strategy.gspmd_builders import Sharded
    from tests.unit.test_end_to_end import make_batch, make_trainable

    for builder in (AllReduce(), PartitionedPS(), Sharded()):
        runner = AutoDist({}, builder).build(make_trainable())
        before = runner.get_params()
        m = runner.eval_step(make_batch())
        assert np.isfinite(float(m["loss"]))
        after = runner.get_params()
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), before, after)
        # evaluate() over several batches
        agg = runner.evaluate([make_batch(s) for s in range(3)])
        assert "loss" in agg and np.isfinite(agg["loss"])


def test_memory_summary_shapes():
    from autodist_tpu.utils import profiling

    # CPU backend exposes no HBM stats -> {}.
    assert profiling.memory_summary() in ({},) or isinstance(
        profiling.memory_summary(), dict)

    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 500, "bytes_limit": 1000,
                    "peak_bytes_in_use": 800, "label": "x"}

    out = profiling.memory_summary(FakeDev())
    assert out["bytes_in_use"] == 500 and out["utilization"] == 0.5
    assert "label" not in out


def test_native_build_falls_back_to_user_cache(monkeypatch, tmp_path):
    """Read-only installs (system site-packages, container layers) build
    the native libraries in XDG_CACHE_HOME instead of next to the
    sources."""
    import os

    from autodist_tpu.runtime import nativelib as nl

    real_access = os.access
    monkeypatch.setattr(
        nl.os, "access",
        lambda p, m: False if p == nl.NATIVE_DIR else real_access(p, m))
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    nl._loaded.clear()
    lib = nl.load_native("libautodist_dataio.so", "dataio.cc")
    assert lib is not None
    cache = tmp_path / "autodist_tpu" / "native"
    assert (cache / "libautodist_dataio.so").exists()
    assert (cache / "dataio.cc").exists()   # sources copied for make
    nl._loaded.clear()                      # don't leak the cache CDLL
