"""Topology-aware strategy search + hierarchical (ICI/DCN) pricing.

Pins the PR-10 contracts: pure-ICI pricing is byte-identical to the
flat model (no silent recalibration), dcn-crossing collectives are
priced at DCN constants and monotone in the slice count, the searched
frontier keeps tp within a slice and rides only data parallelism
across DCN (both directions: a hand-made DCN-crossing-tp plan prices
strictly worse AND plan-lints ADT060), the searched winner never
scores below the zoo winner, and the full cross-product for an
8-device two-slice fixture enumerates/prunes/prices in bounded time
with a program-lint-clean winner.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, AutoStrategy, Trainable
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.resource import CHIP_SPECS, LinkSpec, ResourceSpec
from autodist_tpu.simulator.cost_model import (COLLECTIVE_ALPHA, CostModel)
from autodist_tpu.simulator.search import (SearchSpace, enumerate_configs,
                                           program_lint_winner,
                                           search_strategies)
from autodist_tpu.strategy.builders import builder_from_knobs
from autodist_tpu.strategy.ir import Strategy
from autodist_tpu.strategy.parallel_builders import Pipeline

VOCAB = 93


def make_lm(layers=2):
    cfg = TransformerConfig(vocab_size=VOCAB, hidden_size=16,
                            num_layers=layers, num_heads=2, mlp_dim=32,
                            max_len=8, dtype=jnp.float32,
                            dropout_rate=0.0, attention_dropout_rate=0.0)
    t = make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                   jax.random.PRNGKey(0))
    t.tokens_per_step = 64
    return t


def lm_batch(batch=8, seq=8):
    r = np.random.RandomState(0)
    return {"x": r.randint(0, VOCAB, (batch, seq)).astype(np.int32),
            "y": r.randint(0, VOCAB, (batch, seq)).astype(np.int32)}


def make_dense(dim=256):
    params = {"w1": jnp.zeros((dim, dim), jnp.float32),
              "w2": jnp.zeros((dim, dim), jnp.float32)}
    return Trainable.from_loss_fn(
        lambda p, b: jnp.mean((b["x"] @ p["w1"] @ p["w2"]) ** 2),
        params, optax.adam(1e-3))


def two_slice_spec(**topo):
    return ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8, "num_slices": 2,
                                      **topo}})


# --------------------------------------------------------------------------- #
# Hierarchical network model / per-level pricing
# --------------------------------------------------------------------------- #
def test_chip_specs_carry_dcn_level():
    for spec in CHIP_SPECS.values():
        levels = spec.link_levels()
        assert isinstance(levels["dcn"], LinkSpec)
        # DCN is strictly the slower, higher-latency level.
        assert levels["dcn"].gbps < levels["ici"].gbps
        assert levels["dcn"].alpha_s > levels["ici"].alpha_s


def test_pure_ici_pricing_byte_identical():
    """Single-slice plans must price exactly as the flat model did: the
    closed-form ring(n) envelope at ici_gbps, zero dcn terms — and the
    DCN constants must not leak in (changing them changes nothing)."""
    t = make_dense(dim=512)
    rs = ResourceSpec({"topology": {"num_devices": 8,
                                    "generation": "v4"}})
    strategy = AllReduce().build(t, rs)
    cost = CostModel(rs).strategy_cost(t, strategy)
    total = sum(i.byte_size for i in t.var_infos())
    ring8 = 2.0 * 7 / 8
    assert cost.dcn_bytes == 0.0 and cost.dcn_time_s == 0.0
    assert cost.comm_bytes == pytest.approx(ring8 * total)
    bw = CHIP_SPECS["v4"].ici_gbps * 1e9
    assert cost.comm_time_s == pytest.approx(
        ring8 * total / bw + COLLECTIVE_ALPHA * cost.num_collectives)
    # no silent recalibration: absurd DCN constants leave pure-ICI
    # pricing untouched
    skewed = CostModel(rs, link_profile={"dcn_gbps": 1e-6,
                                         "dcn_alpha_s": 10.0})
    cost2 = skewed.strategy_cost(t, strategy)
    assert cost2.comm_bytes == cost.comm_bytes
    assert cost2.comm_time_s == cost.comm_time_s


def test_dcn_crossing_grad_sync_monotone_in_slices():
    """Raising num_slices at a fixed device count must raise the
    predicted grad-sync time (the flat model priced every slice count
    identically at ici_gbps) — for the collective AND pipeline paths."""
    t = make_dense(dim=512)
    costs = []
    for slices in (1, 2, 4):
        rs = ResourceSpec({"topology": {"num_devices": 8,
                                        "num_slices": slices,
                                        "generation": "v4"}})
        costs.append(CostModel(rs).strategy_cost(
            t, AllReduce().build(t, rs)))
    assert costs[0].comm_time_s < costs[1].comm_time_s \
        < costs[2].comm_time_s
    assert costs[0].dcn_bytes == 0.0
    assert 0.0 < costs[1].dcn_bytes < costs[2].dcn_bytes
    # the cross-slice term is priced at the DCN constants: halving
    # dcn_gbps inflates only the dcn wire term
    rs2 = ResourceSpec({"topology": {"num_devices": 8, "num_slices": 2,
                                     "generation": "v4"}})
    base = CostModel(rs2).strategy_cost(t, AllReduce().build(t, rs2))
    slow = CostModel(rs2, link_profile={
        "dcn_gbps": CHIP_SPECS["v4"].dcn_gbps / 2}).strategy_cost(
            t, AllReduce().build(t, rs2))
    assert slow.dcn_time_s > base.dcn_time_s
    assert slow.comm_time_s - base.comm_time_s == pytest.approx(
        slow.dcn_time_s - base.dcn_time_s)

    # pipeline lowering: same monotonicity for the stage grad sync
    lm = make_lm()
    pipe_costs = []
    for mesh in ({"data": 4, "pipe": 2},
                 {"dcn": 2, "data": 2, "pipe": 2}):
        rs = ResourceSpec({"topology": {"platform": "cpu",
                                        "num_devices": 8},
                           "mesh": mesh})
        pipe_costs.append(CostModel(rs).strategy_cost(
            lm, Pipeline(num_microbatches=2).build(lm, rs)))
    assert pipe_costs[0].dcn_time_s == 0.0
    assert pipe_costs[1].dcn_time_s > 0.0
    assert pipe_costs[1].comm_time_s > pipe_costs[0].comm_time_s


def test_explicit_mesh_without_dcn_axis_still_prices_hierarchically():
    """A declared multi-slice topology whose explicit mesh omits the
    dcn axis still crosses slices with its data axis — pricing it flat
    would be exactly the mispricing the hierarchical model fixes."""
    t = make_dense(dim=512)
    rs = ResourceSpec({"topology": {"num_devices": 8, "num_slices": 2,
                                    "generation": "v4"},
                       "mesh": {"data": 8}})
    cost = CostModel(rs).strategy_cost(t, AllReduce().build(t, rs))
    assert cost.dcn_bytes > 0 and cost.dcn_time_s > 0
    # ... and matches the same topology with the level named
    rs_named = ResourceSpec({"topology": {"num_devices": 8,
                                          "num_slices": 2,
                                          "generation": "v4"}})
    named = CostModel(rs_named).strategy_cost(
        t, AllReduce().build(t, rs_named))
    assert cost.comm_time_s == pytest.approx(named.comm_time_s)


def test_dcn_crossing_tp_prices_strictly_worse_and_lints():
    """Both directions of the tp-stays-within-a-slice contract: a plan
    whose Megatron boundaries span slices prices strictly worse than
    the same degree within a slice, AND plan lint flags it (ADT060)."""
    from autodist_tpu.analysis import lint_plan

    lm = make_lm()
    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"dcn": 2, "data": 1, "pipe": 2,
                                "model": 2}})
    within = Pipeline(num_microbatches=2, tensor_parallel=2).build(lm, rs)
    d = json.loads(within.to_json())
    for nc in d["node_configs"]:
        part = nc.get("partitioner")
        if part and part.get("spec") and "model" in part["spec"]:
            part["spec"] = ["dcn" if a == "model" else a
                            for a in part["spec"]]
    crossing = Strategy.from_json(json.dumps(d))
    cm = CostModel(rs)
    c_within = cm.strategy_cost(lm, within)
    c_cross = cm.strategy_cost(lm, crossing)
    assert c_cross.comm_time_s > c_within.comm_time_s
    assert c_cross.dcn_time_s > c_within.dcn_time_s
    report = lint_plan(crossing, resource_spec=rs, trainable=lm)
    assert "ADT060" in {diag.code for diag in report.errors}
    clean = lint_plan(within, resource_spec=rs, trainable=lm)
    assert "ADT060" not in clean.codes()


# --------------------------------------------------------------------------- #
# The search
# --------------------------------------------------------------------------- #
def test_enumerate_keeps_tp_and_pp_within_a_slice():
    lm = make_lm()
    configs = enumerate_configs(lm, two_slice_spec())
    assert len(configs) >= 300      # a real cross-product, not a zoo
    for cfg in configs:
        assert cfg.dp_dcn == 2      # dcn carries ONLY data parallelism
        assert cfg.dp_ici * cfg.pp * cfg.tp == 4   # within one slice
        mesh = cfg.mesh()
        assert mesh.get("dcn") == 2
        # the model/pipe axes never absorb the slice count
        assert mesh.get("model", 1) * mesh.get("pipe", 1) <= 4


def test_two_slice_search_elects_dp_across_dcn_tp_within_ici():
    """The marquee acceptance: on a two-slice topology the search
    elects a plan that keeps tp within a slice and rides only data
    parallelism across DCN, with the cross-slice term priced at DCN
    constants."""
    lm = make_lm()
    spec = two_slice_spec()
    # resolved mesh / make_mesh / search agree the dcn axis exists
    assert spec.resolved_mesh_shape() == {"dcn": 2, "data": 4}
    assert "dcn" in spec.make_mesh().axis_names
    res = search_strategies(lm, spec, SearchSpace(tp=(2,)),
                            global_batch=8)
    assert res.topology.get("dcn") == 2
    assert res.winner is not None
    win = res.winner
    assert win.config.tp == 2 and win.config.dp_dcn == 2
    assert win.strategy.graph_config.mesh_axes.get("dcn") == 2
    assert win.strategy.graph_config.mesh_axes.get("model") == 2
    # no frontier candidate shards any variable over dcn
    for cand in res.frontier:
        for nc in cand.strategy.node_configs:
            if nc.partitioner is not None and nc.partitioner.spec:
                flat = [a for e in nc.partitioner.spec
                        for a in (e if isinstance(e, (list, tuple))
                                  else [e])]
                assert "dcn" not in flat, cand.name
    # the cross-slice term is real and priced at the DCN constants
    assert win.cost.dcn_time_s > 0.0
    assert win.cost.comm_time_s >= win.cost.dcn_time_s


def test_search_winner_lowers_and_trains_on_original_spec():
    """End-to-end: the winner's own mesh factorization (carried in
    graph_config.mesh_axes) lowers + compiles + steps through AutoDist
    built with the ORIGINAL (mesh-less) two-slice spec."""
    lm = make_lm()
    spec = two_slice_spec()
    res = search_strategies(lm, spec, global_batch=8)
    runner = AutoDist(spec, "AllReduce").build(lm, res.winner.strategy)
    try:
        m = runner.step(lm_batch())
        assert np.isfinite(float(np.asarray(m["loss"])))
    finally:
        runner.close()


def test_searched_winner_matches_or_beats_zoo():
    """On a single-slice topology the searched winner matches or beats
    the zoo winner by predicted score — on every existing fixture
    family (generic trainable, pipeline LM)."""
    fixtures = [
        (make_dense(),
         ResourceSpec({"topology": {"num_devices": 8,
                                    "generation": "v4"}})),
        (make_lm(),
         ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                       "mesh": {"data": 2, "pipe": 2, "model": 2}})),
    ]
    for trainable, spec in fixtures:
        zoo = AutoStrategy()
        zoo.build(trainable, spec)
        # The zoo scores candidates a stage-structured trainable could
        # never lower (AllReduce on the pipeline LM); compare against
        # the best zoo candidate of the trainable's own family — the
        # search seeds exactly those.
        stage = getattr(trainable, "num_stages", None) is not None
        zoo_best = min(
            cost.score for name, cost in zoo.report
            if (name.startswith("Pipeline") == stage))
        searched = AutoStrategy(search=True)
        searched.build(trainable, spec)
        assert searched.report[0][1].score <= zoo_best, \
            (searched.report[0], zoo_best)
        assert searched.search_result is not None


def test_full_cross_product_bounded_time_and_lint_clean():
    """The 8-device two-slice fixture: several hundred raw configs
    enumerate, prune, and price in bounded time; zero plan-lint ERRORs
    among priced survivors; the winner's compiled program lints clean.
    """
    from autodist_tpu.analysis import lint_plan

    lm = make_lm()
    spec = two_slice_spec()
    t0 = time.perf_counter()
    res = search_strategies(lm, spec, global_batch=8)
    elapsed = time.perf_counter() - t0
    assert elapsed < 60.0, f"search took {elapsed:.1f}s"
    assert res.raw_configs >= 300
    assert res.pruned_dominated > 0          # dominance actually fires
    assert res.priced > 0
    assert res.lint_pruned == []             # synthesis emits valid plans
    for cand in res.frontier:
        rep = lint_plan(cand.strategy, resource_spec=cand.spec,
                        trainable=lm)
        assert not rep.errors, (cand.name,
                                [str(d) for d in rep.errors])
    prog = program_lint_winner(res, lm, lm_batch(), vocab_size=VOCAB)
    assert not prog.errors, [str(d) for d in prog.errors]


def test_search_report_breaks_out_per_level_comm():
    lm = make_lm()
    res = search_strategies(lm, two_slice_spec(), global_batch=8)
    text = res.report()
    assert "raw configs" in text and "pruned by dominance" in text \
        and "pruned by lint" in text and "priced" in text
    assert "dcn_MB" in text and "dcn_ms" in text
    assert f"winner: {res.winner.name}" in text


def test_memory_bound_search_elects_memory_lever():
    """The vocab × ZeRO × tp memory interplay the zoo leaves on the
    table: when HBM binds below the replicated footprint, the searched
    winner must be a memory-lever config (ZeRO>=2, vocab_parallel, or
    tp) that the feasibility gate admits."""
    lm = make_lm()
    spec = ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8}})
    cm0 = CostModel(spec)
    replicated = cm0.strategy_cost(
        lm, Pipeline(num_microbatches=1,
                     virtual_stages=2).build(
            lm, spec.with_mesh({"pipe": 1, "data": 8})))
    # budget between the replicated footprint and zero: only sharded
    # configs survive the gate
    headroom = replicated.mem_bytes_per_device * 0.6 \
        / (cm0.chip.hbm_gb * 1e9)
    res = search_strategies(lm, spec, global_batch=8,
                            hbm_headroom=headroom)
    win = res.winner
    assert win.cost.feasible
    assert win.cost.mem_bytes_per_device \
        < replicated.mem_bytes_per_device
    cfg = win.config
    assert cfg is None or cfg.zero_stage >= 2 or cfg.vocab_parallel \
        or cfg.tp > 1 or cfg.pp > 1


# --------------------------------------------------------------------------- #
# builder_from_knobs
# --------------------------------------------------------------------------- #
def test_builder_from_knobs_families():
    from autodist_tpu.strategy.builders import ZeRO
    from autodist_tpu.strategy.gspmd_builders import TensorParallel

    b = builder_from_knobs({"pp": 2, "tp": 2, "num_microbatches": 4,
                            "zero_stage": 3,
                            "collective_precision": "int8"})
    assert isinstance(b, Pipeline)
    assert b.zero_stage == 3 and b.tensor_parallel == 2
    # precision resolved onto only the boundaries this knob set emits
    assert b.precision == {"tp_psum": "int8", "zero3_gather": "int8"}

    assert isinstance(builder_from_knobs({"tp": 4},
                                         stage_structured=False),
                      TensorParallel)
    assert isinstance(builder_from_knobs({"zero_stage": 3},
                                         stage_structured=False),
                      ZeRO)
    assert isinstance(builder_from_knobs({}, stage_structured=False),
                      AllReduce)
    with pytest.raises(ValueError, match="no realization"):
        builder_from_knobs({"vocab_parallel": True},
                           stage_structured=False)
    # knobs must never drop silently: a compressor has no home under
    # GSPMD tp, and an orphan precision string is rejected too
    with pytest.raises(ValueError, match="compressor"):
        builder_from_knobs({"tp": 4, "compressor": "bf16_ef"},
                           stage_structured=False)
    with pytest.raises(ValueError, match="no boundary"):
        builder_from_knobs({"zero_stage": 1,
                            "collective_precision": "int8"})


# --------------------------------------------------------------------------- #
# Drift report: per-level terms + dcn_gbps proposal
# --------------------------------------------------------------------------- #
def test_drift_report_proposes_dcn_gbps():
    """A two-slice run whose measured step is slower than predicted
    proposes measured `link` constants for BOTH levels — the dcn
    analog of the ici_gbps fit."""
    from autodist_tpu.telemetry.drift import drift_report

    t = make_dense(dim=512)
    rs = ResourceSpec({"topology": {"num_devices": 8, "num_slices": 2,
                                    "generation": "v4"}})
    cm = CostModel(rs)
    strategy = AllReduce().build(t, rs)
    predicted = cm.strategy_cost(t, strategy)
    assert predicted.dcn_time_s > 0
    report = drift_report(
        strategy, cm,
        {"step": {"p50_ms": predicted.comm_time_s * 1e3 * 10}},
        trainable=t)
    assert report["predicted"]["comm_time_dcn_s"] == pytest.approx(
        predicted.dcn_time_s)
    assert report["predicted"]["dcn_bytes"] == pytest.approx(
        predicted.dcn_bytes)
    link = (report["proposal"] or {}).get("link", {})
    assert "dcn_gbps" in link and "ici_gbps" in link
    assert 0 < link["dcn_gbps"] < CHIP_SPECS["v4"].dcn_gbps
