"""Sequence-parallel training: golden equality with single-device.

A causal attention LM whose sequence dim is sharded over the ``seq``
mesh axis (ring attention for global context, global_positions for the
positional embedding) must reproduce the unsharded single-device run.
"""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.capture import Trainable
from autodist_tpu.parallel.ring_attention import (make_ring_attention_fn,
                                                  ring_self_attention)
from autodist_tpu.parallel.sequence import (global_positions,
                                            lower_sequence_parallel)

pytestmark = pytest.mark.slow

VOCAB, DIM, HEADS, SEQ = 64, 32, 2, 32


class TinyCausalLM(nn.Module):
    """Single attention block + tied decode; attention/positions are
    pluggable so the same params run sharded and unsharded."""

    attention: any
    positions: any  # (local_len) -> global position ids

    @nn.compact
    def __call__(self, tokens):
        B, L = tokens.shape
        embed = nn.Embed(VOCAB, DIM, name="embed")
        pos_table = self.param("pos", nn.initializers.normal(0.02),
                               (SEQ, DIM))
        x = embed(tokens) + pos_table[self.positions(L)]
        qkv = nn.Dense(3 * DIM, name="qkv")(x).reshape(B, L, 3, HEADS,
                                                       DIM // HEADS)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = self.attention(q, k, v).reshape(B, L, DIM)
        x = x + nn.Dense(DIM, name="out")(o)
        x = nn.LayerNorm(name="ln")(x)
        return embed.attend(x)


def plain_causal_attention(q, k, v):
    depth = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(depth)
    L = q.shape[1]
    mask = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def make_trainable(sharded: bool):
    if sharded:
        attn = lambda q, k, v: ring_self_attention(q, k, v, axis_name="seq",
                                                   causal=True)
        pos = lambda L: global_positions(L)
    else:
        attn = plain_causal_attention
        pos = lambda L: jnp.arange(L)
    model = TinyCausalLM(attention=attn, positions=pos)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["y"][..., None], axis=-1)
        return -jnp.mean(ll)

    # init unsharded (positions 0..L)
    init_model = TinyCausalLM(attention=plain_causal_attention,
                              positions=lambda L: jnp.arange(L))
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((2, SEQ), jnp.int32))["params"]
    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.5))


def batches(n):
    r = np.random.RandomState(0)
    out = []
    for _ in range(n):
        x = r.randint(0, VOCAB, (8, SEQ)).astype(np.int32)
        out.append({"x": x, "y": np.roll(x, -1, axis=1)})
    return out


def test_sequence_parallel_matches_single_device():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "seq"))

    trainable = make_trainable(sharded=True)
    init_fn, step_fn, _ = lower_sequence_parallel(trainable, mesh)
    state = init_fn(trainable.params, None)
    bs = batches(3)
    for b in bs:
        state, metrics = step_fn(state, jax.tree.map(jnp.asarray, b),
                                 jax.random.PRNGKey(0))

    # single-device reference with plain attention, full sequences
    ref = make_trainable(sharded=False)
    params = ref.params
    opt_state = ref.optimizer.init(params)
    for b in bs:
        def loss_for(p):
            l, _, _ = ref.loss(p, None, jax.tree.map(jnp.asarray, b),
                               jax.random.PRNGKey(0))
            return l
        grads = jax.grad(loss_for)(params)
        updates, opt_state = ref.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-5, atol=2e-5),
        jax.device_get(state["params"]), jax.device_get(params))


def test_sequence_parallel_seq_only_mesh():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    trainable = make_trainable(sharded=True)
    init_fn, step_fn, _ = lower_sequence_parallel(trainable, mesh)
    state = init_fn(trainable.params, None)
    b = batches(1)[0]
    state, metrics = step_fn(state, jax.tree.map(jnp.asarray, b),
                             jax.random.PRNGKey(0))
    assert np.isfinite(float(np.asarray(metrics["loss"])))


def test_sequence_parallel_rejects_unmatched_leaves():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    trainable = make_trainable(sharded=True)
    init_fn, step_fn, _ = lower_sequence_parallel(trainable, mesh)
    state = init_fn(trainable.params, None)
    b = batches(1)[0]
    bad = {"tokens": b["x"], "labels": b["y"]}  # not in seq_leaves
    with pytest.raises(ValueError, match="seq_leaves"):
        step_fn(state, jax.tree.map(jnp.asarray, bad),
                jax.random.PRNGKey(0))


def test_sequence_parallel_ring_flash_matches_single_device():
    """Same golden bar with the Pallas per-chunk ring: parameters after
    training must equal the unsharded single-device run."""
    from jax.sharding import Mesh

    from autodist_tpu.parallel.ring_attention import ring_flash_attention

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "seq"))

    # Build the flash variant the same way make_trainable does.
    attn = lambda q, k, v: ring_flash_attention(q, k, v, axis_name="seq",
                                                causal=True)
    pos = lambda L: global_positions(L)
    model = TinyCausalLM(attention=attn, positions=pos)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["y"][..., None], axis=-1)
        return -jnp.mean(ll)

    flash_trainable = Trainable.from_loss_fn(
        loss_fn, make_trainable(sharded=False).params, optax.sgd(0.5))

    init_fn, step_fn, _ = lower_sequence_parallel(flash_trainable, mesh)
    state = init_fn(flash_trainable.params, None)
    bs = batches(3)
    for b in bs:
        state, _ = step_fn(state, jax.tree.map(jnp.asarray, b),
                           jax.random.PRNGKey(0))

    # Single-device reference: plain optax loop, unsharded attention.
    ref_t = make_trainable(sharded=False)
    ref = jax.tree.map(jnp.asarray, ref_t.params)
    opt = optax.sgd(0.5)
    opt_state = opt.init(ref)
    for b in bs:
        grads = jax.grad(lambda p, bb: ref_t.loss(p, None, bb, None)[0])(
            ref, jax.tree.map(jnp.asarray, b))
        updates, opt_state = opt.update(grads, opt_state, ref)
        ref = optax.apply_updates(ref, updates)

    got = jax.device_get(state["params"])
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-5),
        got, jax.device_get(ref))


def test_global_positions_static_max_len_check():
    """A positional table too small for shards x local_len fails at trace
    time (both quantities are static inside shard_map)."""
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))

    def f():
        return global_positions(16, max_len=32)  # 4 shards x 16 > 32

    with pytest.raises(ValueError, match="does not cover"):
        jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(), out_specs=P("seq"),
                              check_vma=False)).lower()


def test_position_fn_out_of_range_poisons_to_nan():
    """Out-of-range position ids must surface as NaN loss on step one,
    not silently-clamped (repeated last-row) embeddings."""
    from autodist_tpu.models.transformer import (TransformerConfig,
                                                 TransformerLM)

    cfg = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=1,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dropout_rate=0.0, attention_dropout_rate=0.0,
                            position_fn=lambda L: jnp.arange(L) + 4)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)  # ids 4..11 vs max_len 8 -> oob
    params = TransformerLM(
        TransformerConfig(vocab_size=32, hidden_size=16, num_layers=1,
                          num_heads=2, mlp_dim=32, max_len=8,
                          dropout_rate=0.0, attention_dropout_rate=0.0)
    ).init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert bool(jnp.isnan(logits).any())
