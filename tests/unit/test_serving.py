"""Serving-path goldens: the batched-inference engine on the Strategy IR.

The decode correctness bar (ISSUE 7 acceptance): greedy decode of the
tp∈{1,2} × vocab-parallel pipelined LM matches the single-device
full-recompute reference token-for-token — including the ``V % tp != 0``
padding edge, where padded vocab rows must never be sampled — and
continuous-batching interleaving (requests joining/leaving mid-flight)
yields exactly the tokens each request gets when run alone.  Plus the
per-token telemetry contract (``kind="serve"`` records through the PR 4
sink, schema-gated by ``tools/telemetry_report.py --check``) and the
cost model's decode-latency objective.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.models.pipeline_lm import (make_pipeline_lm_trainable,
                                             sequential_logits)
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.serving import (ContinuousBatcher, ServingEngine,
                                  init_cache, serve)
from autodist_tpu.serving import kv_cache

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V = 33          # odd: V % 2 != 0 exercises the vocab zero-pad path
MAX_LEN = 24


def make_cfg(vocab=V, max_len=MAX_LEN):
    return TransformerConfig(
        vocab_size=vocab, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_len=max_len, dtype=jnp.float32,
        dropout_rate=0.0, attention_dropout_rate=0.0)


@pytest.fixture(scope="module")
def cfg():
    return make_cfg()


@pytest.fixture(scope="module")
def params(cfg):
    return make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params


def reference_greedy(cfg, params, prompt, n):
    """Single-device reference: full-sequence recompute per emitted
    token — no KV cache, no masking tricks, the training stack's own
    layer/loss-head math (:func:`sequential_logits`)."""
    toks = list(prompt)
    for _ in range(n):
        logits = sequential_logits(cfg, params,
                                   jnp.asarray(toks)[None])
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def make_engine(cfg, params, tp=1, vocab_parallel=False, slots=2,
                decode_steps=3, prefill_len=8):
    return ServingEngine(cfg, params, tensor_parallel=tp,
                         vocab_parallel=vocab_parallel, num_slots=slots,
                         max_len=cfg.max_len, prefill_len=prefill_len,
                         decode_steps=decode_steps)


# --------------------------------------------------------------------- #
# KV cache
# --------------------------------------------------------------------- #
def test_kv_cache_layout_and_token_writes():
    c = init_cache(num_layers=2, num_slots=3, num_heads=4, head_dim=5,
                   max_len=7)
    assert c.k.shape == (2, 3, 4, 7, 5)       # [L, B, heads, T, dh]
    kv = jnp.arange(3 * 1 * 4 * 5, dtype=jnp.float32).reshape(3, 1, 4, 5)
    positions = jnp.array([0, 2, 6], jnp.int32)
    k = kv_cache.write_token(c.k, 1, kv, positions)
    for slot, pos in enumerate([0, 2, 6]):
        np.testing.assert_array_equal(np.asarray(k[1, slot, :, pos, :]),
                                      np.asarray(kv[slot, 0]))
    assert float(jnp.abs(k[0]).sum()) == 0.0   # other layer untouched
    # every non-written position stays zero
    mask = np.ones((3, 4, 7, 5), bool)
    for slot, pos in enumerate([0, 2, 6]):
        mask[slot, :, pos, :] = False
    assert float(jnp.abs(jnp.asarray(np.asarray(k[1])[mask])).sum()) == 0.0


def test_kv_cache_prompt_writes_respect_admit_mask():
    c = init_cache(num_layers=1, num_slots=2, num_heads=2, head_dim=3,
                   max_len=6)
    resident = c.k + 7.0        # slot state that must survive
    kv = jnp.ones((2, 4, 2, 3), jnp.float32)       # [B, S, heads, dh]
    admit = jnp.array([True, False])
    k = kv_cache.write_prompt(resident, 0, kv, admit)
    assert float(k[0, 0, :, :4, :].min()) == 1.0   # admitted: new rows
    np.testing.assert_array_equal(np.asarray(k[0, 1]),
                                  np.asarray(resident[0, 1]))


def test_cached_attention_masks_beyond_length():
    """Entries past a slot's occupancy are unreachable: garbage written
    there must not change the attention output."""
    B, H, T, D = 2, 2, 6, 4
    q = jnp.asarray(np.random.RandomState(0).randn(B, 1, H, D), jnp.float32)
    k = jnp.asarray(np.random.RandomState(1).randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(np.random.RandomState(2).randn(B, H, T, D), jnp.float32)
    lengths = jnp.array([2, 4], jnp.int32)
    out = kv_cache.cached_attention(q, k, v, lengths)
    poison = jnp.where(
        (jnp.arange(T) > lengths[:, None])[:, None, :, None], 1e9, 0.0)
    out2 = kv_cache.cached_attention(q, k + poison, v + poison, lengths)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# --------------------------------------------------------------------- #
# greedy decode goldens (the acceptance bar)
# --------------------------------------------------------------------- #
PROMPT = [3, 1, 4, 1, 5]


@pytest.mark.parametrize("tp,vocab_parallel", [(1, False), (2, False),
                                               (2, True)])
def test_greedy_decode_matches_sequential_reference(cfg, params, tp,
                                                    vocab_parallel):
    """Token-for-token parity of the KV-cache incremental decode vs the
    full-recompute reference, across tp∈{1,2} × vocab-parallel — with
    V=33 odd, so the vocab-parallel case runs the zero-pad edge and a
    sampled padded row (id >= 33) would break equality immediately."""
    want = reference_greedy(cfg, params, PROMPT, 9)
    engine = make_engine(cfg, params, tp=tp, vocab_parallel=vocab_parallel)
    b = ContinuousBatcher(engine)
    rid = b.submit(PROMPT, max_new_tokens=9)
    got = b.run()[rid].tokens
    assert got == want
    assert all(0 <= t < cfg.vocab_size for t in got)


def test_padded_vocab_rows_never_win_greedy():
    """Adversarial pad-row check: hidden states crafted so every REAL
    vocab row scores negative while the zero-padded row would score 0
    (the max) if it weren't masked."""
    from jax.sharding import Mesh, PartitionSpec as P

    from autodist_tpu.parallel.tensor import vocab_parallel_greedy_token

    vocab, H, tp = 5, 8, 2                     # pads to 6 rows, 3/shard
    rng = np.random.RandomState(0)
    # all-positive rows + all-negative hidden state: every real row's
    # logit is strictly negative, while the padded all-zero row would
    # score exactly 0 (the max) if it weren't masked
    emb = jnp.asarray(np.abs(rng.randn(vocab, H)) + 0.1, jnp.float32)
    x = -jnp.ones((1, H), jnp.float32)
    logits = np.asarray(x @ emb.T)[0]
    assert (logits < 0).all(), "construction failed to go negative"
    emb_pad = jnp.pad(emb, ((0, 1), (0, 0)))   # padded row -> logit 0
    mesh = Mesh(np.array(jax.devices()[:tp]), ("model",))

    def run(xx, ee):
        tok, _ = vocab_parallel_greedy_token(xx, ee, vocab_size=vocab,
                                             model_axis="model")
        return tok

    tok = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P(), P("model", None)),
        out_specs=P(), check_vma=False))(x, emb_pad)
    assert int(tok[0]) == int(np.argmax(logits))
    assert int(tok[0]) < vocab


def test_continuous_batching_interleave_parity(cfg, params):
    """Requests joining and leaving mid-flight (3 requests, 2 slots:
    the third admits only when a slot frees) decode exactly the tokens
    each gets when run alone."""
    reqs = [([3, 1, 4], 10), ([2, 7], 4), ([5, 5, 5, 5, 9], 7)]
    eng = make_engine(cfg, params)
    b = ContinuousBatcher(eng)
    rids = [b.submit(p, max_new_tokens=m) for p, m in reqs]
    inter = b.run()
    assert set(inter) == set(rids)
    for (p, m), rid in zip(reqs, rids):
        solo = ContinuousBatcher(make_engine(cfg, params))
        srid = solo.submit(p, max_new_tokens=m)
        assert inter[rid].tokens == solo.run()[srid].tokens
        # ... and both match the sequential reference
        assert inter[rid].tokens == reference_greedy(cfg, params, p, m)


def test_batcher_queue_eviction_and_eos(cfg, params):
    eng = make_engine(cfg, params, slots=1)
    b = ContinuousBatcher(eng)
    # discover this prompt's greedy stream, then stop at its 3rd token
    probe = ContinuousBatcher(make_engine(cfg, params, slots=1))
    probe_rid = probe.submit(PROMPT, max_new_tokens=8)
    stream = probe.run()[probe_rid].tokens
    eos = stream[2]
    first_eos = stream.index(eos)
    r1 = b.submit(PROMPT, max_new_tokens=8, eos_id=eos)
    r2 = b.submit([2, 7, 1], max_new_tokens=5)    # queued behind r1
    assert b.active_slots == 0 and len(b._queue) == 2
    done = b.run()
    assert done[r1].finish_reason == "eos"
    assert done[r1].tokens == stream[:first_eos + 1]
    assert done[r2].finish_reason == "max_tokens"
    assert len(done[r2].tokens) == 5
    assert done[r2].queue_wait_s >= 0.0
    assert done[r1].ttft_s > 0 and done[r1].tokens_per_sec > 0


def test_eos_beyond_budget_does_not_stretch_request(cfg, params):
    """An EOS landing past max_new_tokens inside the same fused window
    must not stretch the request: the budget caps first."""
    probe = ContinuousBatcher(make_engine(cfg, params, slots=1))
    probe_rid = probe.submit(PROMPT, max_new_tokens=8)
    stream = probe.run()[probe_rid].tokens
    late = next((t for t in stream[2:] if t not in stream[:2]), None)
    assert late is not None, f"degenerate stream {stream}"
    b = ContinuousBatcher(make_engine(cfg, params, slots=1))
    rid = b.submit(PROMPT, max_new_tokens=2, eos_id=late)
    out = b.run()[rid]
    assert out.finish_reason == "max_tokens"
    assert out.tokens == stream[:2]
    assert len(out.inter_token_ms) <= 2   # discarded tokens not timed


def test_run_returns_only_new_completions(cfg, params):
    """A long-lived loop calling run() per admission round must not
    re-receive old completions (the full history stays on
    .completions)."""
    b = ContinuousBatcher(make_engine(cfg, params))
    r1 = b.submit(PROMPT, max_new_tokens=3)
    first = b.run()
    assert set(first) == {r1}
    r2 = b.submit([2, 7], max_new_tokens=3)
    second = b.run()
    assert set(second) == {r2}
    assert set(b.completions) == {r1, r2}


def test_batcher_max_len_eviction(cfg, params):
    """A request whose budget exceeds the cache capacity evicts at
    max_len with the over-capacity tail truncated deterministically."""
    eng = make_engine(cfg, params, slots=2)
    b = ContinuousBatcher(eng)
    rid = b.submit(PROMPT, max_new_tokens=200)
    out = b.run()[rid]
    assert out.finish_reason == "max_len"
    assert len(out.tokens) == cfg.max_len - len(PROMPT)


def test_batcher_validates_requests(cfg, params):
    b = ContinuousBatcher(make_engine(cfg, params))
    with pytest.raises(ValueError, match="empty prompt"):
        b.submit([])
    with pytest.raises(ValueError, match="prefill_len"):
        b.submit(list(range(20)))
    with pytest.raises(ValueError, match="max_new_tokens"):
        b.submit([1], max_new_tokens=0)


# --------------------------------------------------------------------- #
# serve() entry + engine config validation
# --------------------------------------------------------------------- #
def test_serve_entry_point_reads_strategy_ir(cfg, params):
    from autodist_tpu.strategy.ir import GraphConfig, Strategy

    strategy = Strategy(node_configs=[], graph_config=GraphConfig(
        replicas=1, lowering="pipeline",
        parallel={"tensor_parallel": 2, "vocab_parallel": True}))
    engine = serve(cfg, params=params, strategy=strategy, num_slots=2,
                   prefill_len=8, decode_steps=2)
    assert engine.tensor_parallel == 2 and engine.vocab_parallel
    with pytest.raises(ValueError, match="exactly one"):
        serve(cfg, params=params, artifact="/tmp/nope")
    with pytest.raises(ValueError, match="exactly one"):
        serve(cfg)


def test_engine_validates_shapes(cfg, params):
    with pytest.raises(ValueError, match="num_heads"):
        ServingEngine(cfg, params, tensor_parallel=4)   # 2 heads % 4
    with pytest.raises(ValueError, match="position table"):
        ServingEngine(cfg, params, max_len=10 * cfg.max_len)
    with pytest.raises(ValueError, match="prefill_len"):
        ServingEngine(cfg, params, prefill_len=cfg.max_len + 1)


# --------------------------------------------------------------------- #
# per-token telemetry through the PR 4 sink
# --------------------------------------------------------------------- #
def test_serving_telemetry_records_and_report(cfg, params, tmp_path):
    tel = telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path), enabled=True)
    try:
        b = ContinuousBatcher(make_engine(cfg, params))
        rids = [b.submit([3, 1, 4], max_new_tokens=4),
                b.submit([2, 7], max_new_tokens=3)]
        b.run()
        paths = telemetry.flush()
    finally:
        telemetry.reset()
    with open(paths["metrics"]) as f:
        recs = [json.loads(line) for line in f]
    serves = {r["request"]: r for r in recs if r.get("kind") == "serve"}
    assert set(serves) == set(rids)
    for rid in rids:
        rec = serves[rid]
        assert rec["ttft_ms"] > 0 and rec["tokens"] >= 1
        assert rec["tokens_per_sec"] > 0
        assert rec["inter_token_p50_ms"] > 0
    counters = {r["name"]: r["value"] for r in recs
                if r.get("kind") == "counter"}
    assert counters["serve/requests"] == 2
    assert counters["serve/tokens"] >= 7
    hists = {r["name"] for r in recs if r.get("kind") == "histogram"}
    assert {"serve/ttft_ms", "serve/inter_token_ms"} <= hists

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    assert telemetry_report.check_schema(str(tmp_path)) == []
    md = telemetry_report.render(str(tmp_path))
    assert "## serving" in md and "ttft" in md

    # the schema gate rejects a serve record missing its latency facts
    with open(os.path.join(tmp_path, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({"kind": "serve", "request": "x"}) + "\n")
    problems = telemetry_report.check_schema(str(tmp_path))
    assert any("serve record missing" in p for p in problems)


def test_record_event_contract():
    tel = telemetry.reset()
    tel.enabled = True
    assert tel.record_event("serve", request="r", tokens=3)
    assert tel.step_records()[-1]["kind"] == "serve"
    with pytest.raises(ValueError, match="record_step"):
        tel.record_event("step", step=1)
    tel.enabled = False
    assert not tel.record_event("serve", request="r2")
    telemetry.reset()


# --------------------------------------------------------------------- #
# the cost model's decode-latency objective
# --------------------------------------------------------------------- #
def test_decode_cost_ranks_tp_by_comm_vs_compute_win(cfg):
    """tp=2 ranks above tp=1 exactly when the per-token comm cost is
    under the compute win — both directions, by link profile."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import CostModel

    trainable = make_pipeline_lm_trainable(
        make_cfg(vocab=512, max_len=64), optax.sgd(0.1),
        jax.random.PRNGKey(0))
    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8}})
    fast = CostModel(rs, link_profile={"ici_gbps": 1e4,
                                       "hop_alpha_s": 1e-9})
    c1 = fast.decode_cost(trainable, {"tensor_parallel": 1})
    c2 = fast.decode_cost(trainable, {"tensor_parallel": 2})
    assert c1.comm_time_s == 0.0
    assert c2.comm_time_s < c1.compute_time_s - c2.compute_time_s
    assert c2.token_time_s < c1.token_time_s          # tp=2 elected
    slow = CostModel(rs, link_profile={"ici_gbps": 1e-4,
                                       "hop_alpha_s": 1e-2})
    d1 = slow.decode_cost(trainable, {"tensor_parallel": 1})
    d2 = slow.decode_cost(trainable, {"tensor_parallel": 2})
    assert d2.comm_time_s > d1.compute_time_s - d2.compute_time_s
    assert d1.token_time_s < d2.token_time_s          # tp=1 elected
    # the KV cache and params shard with tp
    assert c2.kv_bytes_per_device == pytest.approx(
        c1.kv_bytes_per_device / 2)
    assert c2.mem_bytes_per_device < c1.mem_bytes_per_device


def test_decode_cost_layer_fallback_ignores_embedding_tables():
    """A trainable without num_stages must not mistake a [V, H]
    embedding's vocab dim for a layer count (it would inflate every
    decode term by orders of magnitude)."""
    from autodist_tpu import Trainable
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import CostModel

    params = {
        "embedding": jnp.zeros((5000, 8), jnp.float32),
        "blocks": {"qkv": jnp.zeros((4, 8, 24), jnp.float32),
                   "wo": jnp.zeros((4, 16, 8), jnp.float32)},
    }
    t = Trainable.from_loss_fn(
        lambda p, b: jnp.sum(p["embedding"]) * 0.0, params,
        optax.sgd(0.1))
    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 2}})
    cost = CostModel(rs).decode_cost(t, {"tensor_parallel": 1},
                                     max_len=64)
    # kv term built from layers=4 (the stacked blocks), not 5000
    assert cost.kv_bytes_per_device < 5000 * 8 * 64
    hidden = CostModel._hidden_dim(t)
    assert cost.kv_bytes_per_device == pytest.approx(
        2.0 * 4 * hidden * 64 * 2.0)


def test_rank_serving_orders_and_reads_strategy(cfg):
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import rank_serving

    trainable = make_pipeline_lm_trainable(
        make_cfg(vocab=512, max_len=64), optax.sgd(0.1),
        jax.random.PRNGKey(0))
    rs = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 4}})
    ranked = rank_serving(trainable, rs,
                          link_profile={"ici_gbps": 1e4,
                                        "hop_alpha_s": 1e-9})
    assert len(ranked) >= 4          # tp1 + tp{2,4} x vocab{off,on}
    scores = [cost.score for _, cost in ranked]
    assert scores == sorted(scores)
    assert ranked[0][1].tensor_parallel > 1       # fast link: tp wins


# --------------------------------------------------------------------- #
# acceptance: examples/serve.py --smoke + telemetry --check (CI smoke)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def serve_smoke_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("serve_tel")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/serve.py"),
         "--smoke", "--telemetry-dir", str(out)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return out, proc.stdout


def test_serve_smoke_subprocess(serve_smoke_run):
    out, stdout = serve_smoke_run
    assert "serve smoke ok" in stdout
    assert "tokens/s aggregate" in stdout
    assert "serving configs by predicted token latency" in stdout
    with open(out / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    serves = [r for r in recs if r.get("kind") == "serve"]
    assert len(serves) == 4
    assert all(r["ttft_ms"] > 0 and r["tokens"] >= 1 for r in serves)


def test_serve_smoke_report_check(serve_smoke_run):
    out, _ = serve_smoke_run
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    assert telemetry_report.main([str(out), "--check"]) == 0
    md = telemetry_report.render(str(out))
    assert "## serving" in md


# --------------------------------------------------------------------- #
# Graceful degradation: deadlines, bounded-queue shedding, drain
# (both-ways: no deadline pressure => completions byte-identical).
# --------------------------------------------------------------------- #
def test_no_deadline_completions_byte_identical(cfg, params):
    """Both-ways golden: a huge deadline and a bounded-but-unfull queue
    decode EXACTLY the tokens the plain batcher decodes."""
    reqs = [([3, 1, 4], 6), ([2, 7], 4)]
    plain = ContinuousBatcher(make_engine(cfg, params))
    plain_rids = [plain.submit(p, max_new_tokens=m) for p, m in reqs]
    plain_out = plain.run()
    guarded = ContinuousBatcher(make_engine(cfg, params), max_queue=16)
    g_rids = [guarded.submit(p, max_new_tokens=m, deadline_s=3600.0)
              for p, m in reqs]
    g_out = guarded.run()
    for pr, gr in zip(plain_rids, g_rids):
        assert g_out[gr].tokens == plain_out[pr].tokens
        assert g_out[gr].finish_reason == plain_out[pr].finish_reason


def test_queued_request_past_deadline_expires_unstarted(cfg, params):
    telemetry.reset()
    b = ContinuousBatcher(make_engine(cfg, params, slots=1))
    live = b.submit([3, 1, 4], max_new_tokens=3)
    doomed = b.submit([2, 7], max_new_tokens=3, deadline_s=1e-4)
    import time as _t

    _t.sleep(0.01)   # the queued deadline passes before any admission
    out = b.run()
    assert out[live].finish_reason == "max_tokens"
    assert out[doomed].finish_reason == "deadline_exceeded"
    assert out[doomed].tokens == []
    assert telemetry.get().registry.counter(
        "serve/deadline_exceeded").value == 1


def test_in_flight_deadline_keeps_partial_tokens(cfg, params):
    """A request whose deadline lapses mid-decode completes with the
    tokens it already has — partial beats nothing at the deadline."""
    b = ContinuousBatcher(make_engine(cfg, params, slots=1,
                                      decode_steps=1))
    rid = b.submit([3, 1, 4], max_new_tokens=64, deadline_s=0.05)
    out = b.run()[rid]
    assert out.finish_reason == "deadline_exceeded"
    assert 0 < len(out.tokens) < 64
    # the partial prefix matches the unconstrained stream
    free = ContinuousBatcher(make_engine(cfg, params, slots=1,
                                         decode_steps=1))
    frid = free.submit([3, 1, 4], max_new_tokens=64)
    assert out.tokens == free.run()[frid].tokens[:len(out.tokens)]


def test_bounded_queue_sheds_with_coded_error(cfg, params):
    from autodist_tpu.serving import OverloadedError

    telemetry.reset()
    b = ContinuousBatcher(make_engine(cfg, params, slots=1), max_queue=1)
    b.submit([3, 1], max_new_tokens=2)
    with pytest.raises(OverloadedError, match="serve/overloaded"):
        b.submit([2, 7], max_new_tokens=2)
    assert telemetry.get().registry.counter("serve/shed").value == 1
    # the shed request never entered: the queued one still completes
    assert len(b.run()) == 1


def test_drain_never_strands_in_flight_slots(cfg, params):
    from autodist_tpu.serving import OverloadedError

    telemetry.reset()
    eng = make_engine(cfg, params, slots=1, decode_steps=1)
    b = ContinuousBatcher(eng)
    flying = b.submit([3, 1, 4], max_new_tokens=6)
    queued = b.submit([2, 7], max_new_tokens=4)     # no free slot
    b.step()                                        # admits `flying` only
    assert b.active_slots == 1
    done = b.drain(finish_in_flight=True)
    # every submitted request ended in exactly one completion
    assert set(done) == {flying, queued}
    assert done[flying].finish_reason == "max_tokens"
    assert len(done[flying].tokens) == 6            # decoded to terminal
    assert done[queued].finish_reason == "shed"     # resubmittable
    assert done[queued].tokens == []
    assert b.active_slots == 0
    with pytest.raises(OverloadedError):            # drained = no admits
        b.submit([5], max_new_tokens=1)


def test_drain_cut_evicts_at_current_token(cfg, params):
    eng = make_engine(cfg, params, slots=1, decode_steps=1)
    b = ContinuousBatcher(eng)
    rid = b.submit([3, 1, 4], max_new_tokens=50)
    b.step()
    b.step()
    done = b.drain(finish_in_flight=False)
    assert done[rid].finish_reason == "drained"
    assert 0 < len(done[rid].tokens) < 50           # cut, tokens kept
