"""Cost model + AutoStrategy selection tests.

The reference shipped only the AutoSync dataset stub
(``autodist/simulator/dataset/README.md``); this validates the working
analytic replacement: cost ordering matches the qualitative facts the
reference documented (best strategy is model-dependent,
``docs/usage/performance.md:13-18``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, AutoStrategy, Parallax,
                          PartitionedPS, Trainable, ZeRO)
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.simulator import CostModel
from autodist_tpu.strategy import builders


def make_trainable(embed_rows=50_000, dense_dim=64):
    """One big embedding (sparse path) + small dense head."""
    params = {
        "embedding": jnp.zeros((embed_rows, 32), jnp.float32),
        "dense": {"w": jnp.zeros((32, dense_dim), jnp.float32)},
    }

    def loss_fn(p, batch):
        emb = p["embedding"][batch["ids"]].mean(axis=1)
        return jnp.mean((emb @ p["dense"]["w"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3),
                                  sparse_params=("embedding",))


def make_dense_trainable(dim=256):
    params = {"w1": jnp.zeros((dim, dim), jnp.float32),
              "w2": jnp.zeros((dim, dim), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w1"] @ p["w2"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3))


@pytest.fixture()
def rs():
    return ResourceSpec({"topology": {"num_devices": 8, "generation": "v4"}})


def cost_for(builder, trainable, rs):
    strategy = builder.build(trainable, rs)
    return CostModel(rs).strategy_cost(trainable, strategy)


def test_sparse_model_prefers_hybrid(rs):
    """Parallax moves only touched embedding rows; AllReduce moves the
    whole table — the cost model must capture that gap."""
    trainable = make_trainable()
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_px = cost_for(Parallax(), trainable, rs)
    assert c_px.comm_bytes < c_ar.comm_bytes / 4


def test_dense_model_allreduce_not_worse(rs):
    trainable = make_dense_trainable()
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_pps = cost_for(PartitionedPS(), trainable, rs)
    assert c_ar.comm_time_s <= c_pps.comm_time_s


def test_sharded_state_reduces_memory(rs):
    trainable = make_dense_trainable(dim=512)
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_zero = cost_for(ZeRO(), trainable, rs)
    assert c_zero.mem_bytes_per_device < c_ar.mem_bytes_per_device


def test_infeasible_when_model_exceeds_hbm():
    rs = ResourceSpec({"topology": {"num_devices": 8, "generation": "v5e"}})
    # ~64 GB of parameters replicated cannot fit a 16 GB v5e chip.
    big = Trainable.from_loss_fn(
        lambda p, b: jnp.sum(p["w"][0]),
        {"w": jax.ShapeDtypeStruct((4_000_000, 4096), jnp.float32)},
        optax.adam(1e-3), detect_sparse=False)
    c_ar = CostModel(rs).strategy_cost(big, AllReduce().build(big, rs))
    assert not c_ar.feasible


def test_auto_strategy_picks_hybrid_for_sparse_model(rs):
    trainable = make_trainable()
    auto = AutoStrategy()
    strategy = auto.build(trainable, rs)
    assert auto.report, "report populated"
    best_name = auto.report[0][0]
    assert best_name in ("Parallax", "PSLoadBalancing", "PartitionedPS")
    emb = strategy.node_config_for("embedding")
    assert emb is not None and emb.synchronizer.kind == "ps"


def test_auto_strategy_trains_end_to_end():
    """The picked strategy must lower and run."""
    trainable = make_trainable(embed_rows=512, dense_dim=16)
    runner = AutoDist({}, AutoStrategy()).build(trainable)
    rng = np.random.RandomState(0)
    batch = {"ids": rng.randint(0, 512, (16, 8)).astype(np.int32)}
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_create_by_name():
    assert isinstance(builders.create("AutoStrategy"), AutoStrategy)
    from autodist_tpu.strategy.gspmd_builders import TensorParallel
    assert isinstance(builders.create("TensorParallel"), TensorParallel)
    with pytest.raises(ValueError, match="unknown strategy builder"):
        builders.create("Bogus")


def make_tp_shaped_trainable(dim=256):
    """Variable names matching the megatron TP rules."""
    params = {"mlp": {"wi": {"kernel": jnp.zeros((dim, 4 * dim))},
                      "wo": {"kernel": jnp.zeros((4 * dim, dim))}}}

    def loss_fn(p, batch):
        h = jax.nn.relu(batch["x"] @ p["mlp"]["wi"]["kernel"])
        return jnp.mean((h @ p["mlp"]["wo"]["kernel"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3))


def test_auto_strategy_includes_gspmd_candidates(rs):
    """FSDPSharded is scored everywhere; TensorParallel's model-axis
    specs are rejected (candidate skipped) when the topology lacks a
    model axis, and scored when it has one."""
    trainable = make_tp_shaped_trainable()
    auto = AutoStrategy()
    auto.build(trainable, rs)
    names = [n for n, _ in auto.report]
    assert "FSDPSharded" in names
    assert "TensorParallel" not in names  # no model axis in topology

    rs2 = ResourceSpec({"topology": {"num_devices": 8, "generation": "v4"},
                        "mesh": {"data": 4, "model": 2}})
    auto2 = AutoStrategy()
    auto2.build(trainable, rs2)
    names2 = [n for n, _ in auto2.report]
    assert "TensorParallel" in names2


def test_gspmd_fsdp_memory_beats_replicated(rs):
    from autodist_tpu.strategy.gspmd_builders import FSDPSharded

    trainable = make_dense_trainable(dim=512)
    cm = CostModel(rs)
    c_fsdp = cm.strategy_cost(
        trainable, FSDPSharded(min_size=1).build(trainable, rs))
    c_ar = cost_for(AllReduce(), trainable, rs)
    assert c_fsdp.mem_bytes_per_device < c_ar.mem_bytes_per_device


def test_auto_strategy_gspmd_pick_trains():
    """When a GSPMD candidate wins, the facade must lower and run it."""
    from autodist_tpu.strategy.gspmd_builders import FSDPSharded

    trainable = make_dense_trainable(dim=64)
    auto = AutoStrategy(candidates=[FSDPSharded(min_size=1)])
    runner = AutoDist({}, auto).build(trainable)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 64).astype(np.float32)}
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_auto_strategy_measured_refinement():
    """measure_top_k times real steps of the analytic top-k and picks the
    measured winner (the hardware-as-simulator AutoSync realization)."""
    from autodist_tpu.strategy.builders import PSLoadBalancing

    trainable = make_dense_trainable(dim=64)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 64).astype(np.float32)}
    auto = AutoStrategy(candidates=[AllReduce(), PSLoadBalancing()],
                        measure_top_k=2, example_batch=batch,
                        measure_steps=2)
    runner = AutoDist({}, auto).build(trainable)
    # Both candidates were timed; the pick is one of them.
    assert set(auto.measured) == {"AllReduce", "PSLoadBalancing"}
    assert all(t > 0 for t in auto.measured.values())
    # The cached winner runner was handed over with *fresh* state: the
    # timed measurement steps must not leak into user training.
    assert runner.step_count == 0
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))
    # From-init equality with an unmeasured build of the same trainable.
    fresh = AutoDist({}, AllReduce()).build(make_dense_trainable(dim=64))
    m_fresh = fresh.step(batch)
    if "AllReduce" == min(auto.measured, key=auto.measured.get):
        np.testing.assert_allclose(np.asarray(m["loss"]),
                                   np.asarray(m_fresh["loss"]), rtol=1e-6)


def test_auto_strategy_measure_requires_batch():
    with pytest.raises(ValueError):
        AutoStrategy(measure_top_k=2)


def test_measured_winner_rng_reset_from_init():
    """The cached winner's rng stream must match a fresh build's: an
    rng-consuming loss (dropout-style) trains identically whether or not
    measurement steps ran first."""
    import jax

    def make():
        params = {"w": jnp.ones((32, 32), jnp.float32) * 0.1}

        def loss_fn(p, batch, rng):
            keep = jax.random.bernoulli(rng, 0.8, batch["x"].shape)
            return jnp.mean(((batch["x"] * keep) @ p["w"]) ** 2)

        return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                      with_rng=True)

    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 32).astype(np.float32)}
    auto = AutoStrategy(candidates=[AllReduce()], measure_top_k=2,
                        example_batch=batch, measure_steps=1)
    measured_runner = AutoDist({}, auto).build(make())
    fresh_runner = AutoDist({}, AllReduce()).build(make())
    for _ in range(3):
        m_meas = measured_runner.step(batch)
        m_fresh = fresh_runner.step(batch)
        np.testing.assert_allclose(np.asarray(m_meas["loss"]),
                                   np.asarray(m_fresh["loss"]), rtol=1e-6)
