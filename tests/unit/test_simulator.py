"""Cost model + AutoStrategy selection tests.

The reference shipped only the AutoSync dataset stub
(``autodist/simulator/dataset/README.md``); this validates the working
analytic replacement: cost ordering matches the qualitative facts the
reference documented (best strategy is model-dependent,
``docs/usage/performance.md:13-18``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import (AllReduce, AutoDist, AutoStrategy, Parallax,
                          PartitionedPS, Trainable, ZeRO)
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.simulator import CostModel
from autodist_tpu.strategy import builders


def make_trainable(embed_rows=50_000, dense_dim=64):
    """One big embedding (sparse path) + small dense head."""
    params = {
        "embedding": jnp.zeros((embed_rows, 32), jnp.float32),
        "dense": {"w": jnp.zeros((32, dense_dim), jnp.float32)},
    }

    def loss_fn(p, batch):
        emb = p["embedding"][batch["ids"]].mean(axis=1)
        return jnp.mean((emb @ p["dense"]["w"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3),
                                  sparse_params=("embedding",))


def make_dense_trainable(dim=256):
    params = {"w1": jnp.zeros((dim, dim), jnp.float32),
              "w2": jnp.zeros((dim, dim), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w1"] @ p["w2"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3))


@pytest.fixture()
def rs():
    return ResourceSpec({"topology": {"num_devices": 8, "generation": "v4"}})


def cost_for(builder, trainable, rs):
    strategy = builder.build(trainable, rs)
    return CostModel(rs).strategy_cost(trainable, strategy)


def test_sparse_model_prefers_hybrid(rs):
    """Parallax moves only touched embedding rows; AllReduce moves the
    whole table — the cost model must capture that gap."""
    trainable = make_trainable()
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_px = cost_for(Parallax(), trainable, rs)
    assert c_px.comm_bytes < c_ar.comm_bytes / 4


def test_dense_model_allreduce_not_worse(rs):
    trainable = make_dense_trainable()
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_pps = cost_for(PartitionedPS(), trainable, rs)
    assert c_ar.comm_time_s <= c_pps.comm_time_s


def test_sharded_state_reduces_memory(rs):
    trainable = make_dense_trainable(dim=512)
    c_ar = cost_for(AllReduce(), trainable, rs)
    c_zero = cost_for(ZeRO(), trainable, rs)
    assert c_zero.mem_bytes_per_device < c_ar.mem_bytes_per_device


def test_infeasible_when_model_exceeds_hbm():
    rs = ResourceSpec({"topology": {"num_devices": 8, "generation": "v5e"}})
    # ~64 GB of parameters replicated cannot fit a 16 GB v5e chip.
    big = Trainable.from_loss_fn(
        lambda p, b: jnp.sum(p["w"][0]),
        {"w": jax.ShapeDtypeStruct((4_000_000, 4096), jnp.float32)},
        optax.adam(1e-3), detect_sparse=False)
    c_ar = CostModel(rs).strategy_cost(big, AllReduce().build(big, rs))
    assert not c_ar.feasible


def test_auto_strategy_picks_hybrid_for_sparse_model(rs):
    trainable = make_trainable()
    auto = AutoStrategy()
    strategy = auto.build(trainable, rs)
    assert auto.report, "report populated"
    best_name = auto.report[0][0]
    assert best_name in ("Parallax", "PSLoadBalancing", "PartitionedPS")
    emb = strategy.node_config_for("embedding")
    assert emb is not None and emb.synchronizer.kind == "ps"


def test_auto_strategy_trains_end_to_end():
    """The picked strategy must lower and run."""
    trainable = make_trainable(embed_rows=512, dense_dim=16)
    runner = AutoDist({}, AutoStrategy()).build(trainable)
    rng = np.random.RandomState(0)
    batch = {"ids": rng.randint(0, 512, (16, 8)).astype(np.int32)}
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_create_by_name():
    assert isinstance(builders.create("AutoStrategy"), AutoStrategy)
    from autodist_tpu.strategy.gspmd_builders import TensorParallel
    assert isinstance(builders.create("TensorParallel"), TensorParallel)
    with pytest.raises(ValueError, match="unknown strategy builder"):
        builders.create("Bogus")


def make_tp_shaped_trainable(dim=256):
    """Variable names matching the megatron TP rules."""
    params = {"mlp": {"wi": {"kernel": jnp.zeros((dim, 4 * dim))},
                      "wo": {"kernel": jnp.zeros((4 * dim, dim))}}}

    def loss_fn(p, batch):
        h = jax.nn.relu(batch["x"] @ p["mlp"]["wi"]["kernel"])
        return jnp.mean((h @ p["mlp"]["wo"]["kernel"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3))


def test_auto_strategy_includes_gspmd_candidates(rs):
    """FSDPSharded is scored everywhere; TensorParallel's model-axis
    specs are rejected (candidate skipped) when the topology lacks a
    model axis, and scored when it has one."""
    trainable = make_tp_shaped_trainable()
    auto = AutoStrategy()
    auto.build(trainable, rs)
    names = [n for n, _ in auto.report]
    assert "FSDPSharded" in names
    assert "TensorParallel" not in names  # no model axis in topology

    rs2 = ResourceSpec({"topology": {"num_devices": 8, "generation": "v4"},
                        "mesh": {"data": 4, "model": 2}})
    auto2 = AutoStrategy()
    auto2.build(trainable, rs2)
    names2 = [n for n, _ in auto2.report]
    assert "TensorParallel" in names2


def test_gspmd_fsdp_memory_beats_replicated(rs):
    from autodist_tpu.strategy.gspmd_builders import FSDPSharded

    trainable = make_dense_trainable(dim=512)
    cm = CostModel(rs)
    c_fsdp = cm.strategy_cost(
        trainable, FSDPSharded(min_size=1).build(trainable, rs))
    c_ar = cost_for(AllReduce(), trainable, rs)
    assert c_fsdp.mem_bytes_per_device < c_ar.mem_bytes_per_device


def test_auto_strategy_gspmd_pick_trains():
    """When a GSPMD candidate wins, the facade must lower and run it."""
    from autodist_tpu.strategy.gspmd_builders import FSDPSharded

    trainable = make_dense_trainable(dim=64)
    auto = AutoStrategy(candidates=[FSDPSharded(min_size=1)])
    runner = AutoDist({}, auto).build(trainable)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 64).astype(np.float32)}
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_auto_strategy_measured_refinement():
    """measure_top_k times real steps of the analytic top-k and picks the
    measured winner (the hardware-as-simulator AutoSync realization)."""
    from autodist_tpu.strategy.builders import PSLoadBalancing

    trainable = make_dense_trainable(dim=64)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 64).astype(np.float32)}
    auto = AutoStrategy(candidates=[AllReduce(), PSLoadBalancing()],
                        measure_top_k=2, example_batch=batch,
                        measure_steps=2)
    runner = AutoDist({}, auto).build(trainable)
    # Both candidates were timed; the pick is one of them.
    assert set(auto.measured) == {"AllReduce", "PSLoadBalancing"}
    assert all(t > 0 for t in auto.measured.values())
    # The cached winner runner was handed over with *fresh* state: the
    # timed measurement steps must not leak into user training.
    assert runner.step_count == 0
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))
    # From-init equality with an unmeasured build of the same trainable.
    fresh = AutoDist({}, AllReduce()).build(make_dense_trainable(dim=64))
    m_fresh = fresh.step(batch)
    if "AllReduce" == min(auto.measured, key=auto.measured.get):
        np.testing.assert_allclose(np.asarray(m["loss"]),
                                   np.asarray(m_fresh["loss"]), rtol=1e-6)


def test_auto_strategy_measure_requires_batch():
    with pytest.raises(ValueError):
        AutoStrategy(measure_top_k=2)


def test_measured_winner_rng_reset_from_init():
    """The cached winner's rng stream must match a fresh build's: an
    rng-consuming loss (dropout-style) trains identically whether or not
    measurement steps ran first."""
    import jax

    def make():
        params = {"w": jnp.ones((32, 32), jnp.float32) * 0.1}

        def loss_fn(p, batch, rng):
            keep = jax.random.bernoulli(rng, 0.8, batch["x"].shape)
            return jnp.mean(((batch["x"] * keep) @ p["w"]) ** 2)

        return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                      with_rng=True)

    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 32).astype(np.float32)}
    auto = AutoStrategy(candidates=[AllReduce()], measure_top_k=2,
                        example_batch=batch, measure_steps=1)
    measured_runner = AutoDist({}, auto).build(make())
    fresh_runner = AutoDist({}, AllReduce()).build(make())
    for _ in range(3):
        m_meas = measured_runner.step(batch)
        m_fresh = fresh_runner.step(batch)
        np.testing.assert_allclose(np.asarray(m_meas["loss"]),
                                   np.asarray(m_fresh["loss"]), rtol=1e-6)


def test_cache_bypass_releases_winner_runner():
    """AutoDist.build with rng/runner kwargs (cache guard fails) must
    drop the measured winner's compiled runner instead of retaining its
    device state alongside the fresh build."""
    import jax

    def make():
        params = {"w": jnp.ones((8, 8), jnp.float32)}
        return Trainable.from_loss_fn(
            lambda p, b: jnp.mean((b["x"] @ p["w"]) ** 2), params,
            optax.sgd(0.1))

    batch = {"x": np.random.RandomState(0).randn(8, 8).astype(np.float32)}
    auto = AutoStrategy(candidates=[AllReduce()], measure_top_k=2,
                        example_batch=batch, measure_steps=1)
    runner = AutoDist({}, auto).build(make(), rng=jax.random.PRNGKey(3))
    assert auto._winner_runner is None
    m = runner.step(batch)
    assert np.isfinite(float(np.asarray(m["loss"])))


# ---------------- "which parallelism" pricing (round-4) ----------------- #
def _shape_only_trainable(shapes: dict, **kw):
    """Trainable whose params are ShapeDtypeStructs — the cost model and
    builders only read shapes/dtypes, so multi-GB models cost nothing."""
    params = {name: jax.ShapeDtypeStruct(tuple(s), jnp.float32)
              for name, s in shapes.items()}
    return Trainable.from_loss_fn(lambda p, b: 0.0, params, optax.sgd(0.1),
                                  **kw)


def test_auto_ranks_parallelisms_when_dp_infeasible():
    """The round-3 verdict bar: AutoStrategy answers 'which parallelism',
    not just 'which DP flavor' — on a model too big to replicate, pure
    DP ranks infeasible and a sharded family (TP / FSDP) wins."""
    # ~8.6 GB of fp32 params (TP-rule-named mlp weights); the cpu chip
    # model has 8 GB HBM x 0.6 headroom = 4.8 GB/device.  Replicated
    # state costs (2 + opt) x params ~ 34 GB (infeasible); data-axis
    # sharding divides by 2 (still infeasible); only the 8-way model
    # axis fits: 34/8 = 4.3 GB.
    big = {}
    for i in range(4):
        big[f"layer_{i}/wi/kernel"] = (8192, 32768)
        big[f"layer_{i}/wo/kernel"] = (32768, 8192)
    t = _shape_only_trainable(big)
    spec = ResourceSpec({"topology": {"platform": "cpu", "generation": "cpu",
                                      "num_devices": 16},
                         "mesh": {"data": 2, "model": 8}})
    auto = AutoStrategy()
    strategy = auto.build(t, spec)
    report = dict(auto.report)
    assert not report["AllReduce"].feasible          # pure DP cannot fit
    assert not report["FSDPSharded"].feasible        # 2-way data axis: no
    assert report["TensorParallel"].feasible         # 8-way model axis: yes
    assert auto.report[0][0] == "TensorParallel"
    assert strategy.graph_config.lowering == "gspmd"


def test_sequence_parallel_wins_activation_bound():
    """Activation-bound regime (long context): with activation hints,
    sequence parallelism is the only feasible candidate — params fit
    everywhere but per-device activations only fit when the token dim is
    sharded."""
    t = _shape_only_trainable(
        {"w": (1024, 1024)},
        tokens_per_step=2_000_000,          # 2M tokens in flight
        act_bytes_per_token=8192.0,         # ~16 GB of activations
        sequence_ready=True)                # model uses ring attention
    spec = ResourceSpec({"topology": {"platform": "cpu", "generation": "cpu",
                                      "num_devices": 8},
                         "mesh": {"data": 2, "seq": 4}})
    auto = AutoStrategy()
    strategy = auto.build(t, spec)
    report = dict(auto.report)
    # DP keeps tokens/replicas = 1M tokens x 4KB = 4.1 GB > 2.88 GB... but
    # sequence divides by all 8 devices: 1.02 GB — feasible.
    assert strategy.graph_config.lowering == "sequence"
    assert report["SequenceParallel"].feasible
    assert not report["AllReduce"].feasible


def test_tp_activation_collectives_priced_with_hint():
    """tokens_per_step prices Megatron row-parallel activation
    allreduces: TP comm strictly grows when the hint is present."""
    from autodist_tpu.strategy.gspmd_builders import TensorParallel

    shapes = {"encoder/out/kernel": (8, 64, 512),
              "encoder/qkv/kernel": (512, 3, 8, 64),
              "encoder/wi/kernel": (512, 2048),
              "encoder/wo/kernel": (2048, 512),
              "token_embed/embedding": (30000, 512)}
    spec = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                         "mesh": {"data": 2, "model": 4}})
    strategy = TensorParallel().build(_shape_only_trainable(shapes), spec)

    bare = CostModel(spec).strategy_cost(_shape_only_trainable(shapes),
                                         strategy)
    hinted = CostModel(spec, tokens_per_step=65536).strategy_cost(
        _shape_only_trainable(shapes), strategy)
    assert hinted.comm_bytes > bare.comm_bytes
    assert hinted.num_collectives > bare.num_collectives


def test_pipeline_candidate_skipped_for_plain_trainables():
    """Pipeline in the default zoo must not poison AutoStrategy for
    non-stage-structured trainables (build raises ValueError -> skip)."""
    t = _shape_only_trainable({"w": (256, 256)})
    spec = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                         "mesh": {"data": 2, "pipe": 4}})
    auto = AutoStrategy()
    auto.build(t, spec)  # must not raise
    assert all(not n.startswith("Pipeline") for n, _ in auto.report)


def test_pipeline_candidate_priced_for_pipeline_trainables():
    from autodist_tpu import PipelineTrainable

    stacked = {"w": jax.ShapeDtypeStruct((4, 4096, 4096), jnp.float32),
               "b": jax.ShapeDtypeStruct((4, 4096), jnp.float32)}
    t = PipelineTrainable(lambda p, x: x, stacked,
                          lambda o, b: (0.0, {}), optax.sgd(0.1),
                          num_stages=4, tokens_per_step=8192,
                          act_bytes_per_token=1024.0)
    spec = ResourceSpec({"topology": {"platform": "cpu", "num_devices": 8},
                         "mesh": {"data": 2, "pipe": 4}})
    auto = AutoStrategy()
    auto.build(t, spec)
    report = dict(auto.report)
    assert "Pipeline" in report
    pipe = report["Pipeline"]
    assert pipe.feasible and pipe.comm_bytes > 0


def test_zero_stage_ladder_memory_and_election():
    """The ZeRO rungs on the pipeline lowering: memory strictly
    decreases stage 0 -> 1 -> 2 -> 3 (param/grad shard terms broken
    out), step-time never improves over replication — so stage 3 ranks
    above replication EXACTLY when the memory budget binds (the
    feasibility gate, not the time term, elects it)."""
    from autodist_tpu import PipelineTrainable
    from autodist_tpu.strategy.parallel_builders import Pipeline

    S = 4
    r_ = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r_.randn(S, 64, 64), jnp.float32)}
    t = PipelineTrainable(lambda p, x: jnp.tanh(x @ p["w"]), stacked,
                          lambda o, b: (jnp.mean(o ** 2), {}),
                          optax.adam(1e-2), num_stages=S)
    spec = ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8},
                         "mesh": {"data": 2, "pipe": 4}})
    cm = CostModel(spec)
    costs = {s: cm.strategy_cost(
        t, Pipeline(num_microbatches=2, zero_stage=s).build(t, spec))
        for s in (0, 1, 2, 3)}
    assert costs[1].mem_bytes_per_device < costs[0].mem_bytes_per_device
    assert costs[2].mem_bytes_per_device < costs[1].mem_bytes_per_device
    assert costs[3].mem_bytes_per_device < costs[2].mem_bytes_per_device
    assert costs[2].grad_shard_bytes < costs[1].grad_shard_bytes
    assert costs[3].param_shard_bytes < costs[2].param_shard_bytes
    # never a step-time win: replication stays ahead when memory is free
    for s in (1, 2, 3):
        assert costs[s].comm_time_s >= costs[0].comm_time_s
    # ... and a tokens hint must NOT turn stage 3 into a phantom speed
    # lever: the gather-hiding credit is floored at the stage-1 rs+ag
    # pair (replication's all-reduce hides behind backprop just as
    # well, unmodeled on both sides).
    t.tokens_per_step = 1 << 14
    hinted = {s: cm.strategy_cost(
        t, Pipeline(num_microbatches=2, zero_stage=s).build(t, spec))
        for s in (0, 1, 3)}
    assert hinted[3].comm_time_s >= hinted[1].comm_time_s
    assert hinted[3].comm_time_s > hinted[0].comm_time_s
    t.tokens_per_step = None
    # shrink the budget between stage-1 and stage-3 footprints: only
    # stage 3 survives the feasibility gate and out-scores everything
    mid = (costs[1].mem_bytes_per_device
           + costs[3].mem_bytes_per_device) / 2
    cm2 = CostModel(spec, hbm_headroom=mid / (cm.chip.hbm_gb * 1e9))
    bound = {s: cm2.strategy_cost(
        t, Pipeline(num_microbatches=2, zero_stage=s).build(t, spec))
        for s in (0, 1, 3)}
    assert not bound[0].feasible and not bound[1].feasible
    assert bound[3].feasible
    assert bound[3].score < bound[0].score


def test_zero_stage_alias_and_validation():
    """zero1=True survives as the stage-1 alias; stage and compressor
    stay mutually exclusive per variable (error names the stage) unless
    zero_min_bytes splits them."""
    from autodist_tpu.strategy.ir import PSSynchronizer
    from autodist_tpu.strategy.parallel_builders import Pipeline

    b = Pipeline(num_microbatches=2, zero1=True)
    assert b.zero_stage == 1
    with pytest.raises(ValueError, match="not both"):
        Pipeline(num_microbatches=2, zero1=True, zero_stage=2)
    with pytest.raises(ValueError, match="zero_stage=2"):
        Pipeline(num_microbatches=2, zero_stage=2, compressor="bf16_ef")
    # the size-split mix carries the stage on its PS side
    mix = Pipeline(num_microbatches=2, zero_stage=3, zero_min_bytes=1,
                   compressor="bf16_ef")
    info = type("I", (), {"byte_size": 8, "is_sparse": False})()
    sync = mix.make_sync(info)
    assert isinstance(sync, PSSynchronizer) and sync.zero_stage == 3
    # the IR round-trips the stage (chief -> worker handoff)
    from autodist_tpu.strategy.ir import synchronizer_from_dict
    clone = synchronizer_from_dict(PSSynchronizer(zero_stage=3).to_dict())
    assert clone.zero_stage == 3
    # pre-stage JSON (no zero_stage key) deserializes to stage 1
    d = PSSynchronizer().to_dict()
    d.pop("zero_stage")
    assert synchronizer_from_dict(d).zero_stage == 1


def test_calibration_file_overrides_factors(tmp_path, monkeypatch):
    import json

    from autodist_tpu.simulator import cost_model as cm

    calib = tmp_path / "calibration.json"
    calib.write_text(json.dumps(
        {"compressor_factor": {"int8_ring": 0.61}}))
    monkeypatch.setitem(cm.COMPRESSOR_FACTOR, "int8_ring", 0.25)
    applied = cm.load_calibration(str(calib))
    assert applied == {"int8_ring": 0.61}
    assert cm.COMPRESSOR_FACTOR["int8_ring"] == 0.61


def test_cpu_provenance_calibration_skipped_on_autoload(tmp_path, monkeypatch):
    """A dev-smoke artifact (calibrate_compressors.py on a CPU mesh) must
    not skew accelerator planning: auto-load (env-var candidate) skips a
    file whose meta records backend=cpu; an explicit path still wins."""
    import json

    from autodist_tpu.simulator import cost_model as cm

    calib = tmp_path / "calibration.json"
    calib.write_text(json.dumps(
        {"compressor_factor": {"int8_ring": 37.4},
         "meta": {"backend": "cpu"}}))
    monkeypatch.setitem(cm.COMPRESSOR_FACTOR, "int8_ring", 0.25)
    monkeypatch.setenv("AUTODIST_TPU_CALIBRATION", str(calib))
    # The cpu-provenance env candidate is skipped; auto-load falls
    # through to the committed repo-root calibration.json (analytic
    # provenance), whose int8_ring matches the default 0.25.
    applied = cm.load_calibration()
    assert applied.get("int8_ring") == 0.25
    assert cm.COMPRESSOR_FACTOR["int8_ring"] == 0.25
    # explicit path overrides the provenance gate
    assert cm.load_calibration(str(calib)) == {"int8_ring": 37.4}
