"""Sparse/embedding synchronization: golden numerics + traffic shape.

The reference's hardest correctness area (SURVEY.md §7 risk (a)): its
sparse path split IndexedSlices gradients by index range
(``partitioner.py:660-684``) and pushed them through sparse accumulators
(``ps_synchronizer.py:476-535``).  Here the equivalent collective path
(``ops/sparse.py``) must (1) reproduce single-device training exactly,
(2) keep full-table collectives out of the compiled step program, and
(3) degrade gracefully to dense gathers for non-lookup uses.
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, Parallax, PartitionedPS, Trainable
from autodist_tpu.ops import ShardedEmbedding, embedding_lookup

VOCAB = 64
DIM = 8
BATCH = 16
SEQ = 4


def make_trainable(optimizer=None, seed=0, vocab=VOCAB):
    rng = np.random.RandomState(seed)
    params = {
        "embedding": jnp.asarray(rng.randn(vocab, DIM) * 0.1, jnp.float32),
        "head": {"w": jnp.asarray(rng.randn(DIM, 1) * 0.1, jnp.float32)},
    }

    def loss_fn(p, batch):
        emb = embedding_lookup(p["embedding"], batch["ids"])  # [B, S, D]
        pooled = emb.mean(axis=1)
        pred = (pooled @ p["head"]["w"])[:, 0]
        return jnp.mean((pred - batch["y"]) ** 2)

    return Trainable.from_loss_fn(
        loss_fn, params, optimizer or optax.sgd(0.1),
        sparse_params=("embedding",))


def make_batch(seed=1, vocab=VOCAB):
    rng = np.random.RandomState(seed)
    # Skewed ids with duplicates (the scatter-add must accumulate them).
    ids = rng.randint(0, vocab, (BATCH, SEQ)).astype(np.int32)
    ids[:, 0] = ids[0, 0]  # hot row shared across the whole batch
    return {"ids": ids, "y": rng.randn(BATCH).astype(np.float32)}


def single_device_reference(trainable, batches):
    params = trainable.params
    opt_state = trainable.optimizer.init(params)

    def loss_for(p, b):
        l, _, _ = trainable.loss(p, None, b, jax.random.PRNGKey(0))
        return l

    for b in batches:
        grads = jax.grad(loss_for)(params, jax.tree.map(jnp.asarray, b))
        updates, opt_state = trainable.optimizer.update(grads, opt_state,
                                                        params)
        params = optax.apply_updates(params, updates)
    return params


@pytest.mark.parametrize("builder", [Parallax, PartitionedPS],
                         ids=["Parallax", "PartitionedPS"])
@pytest.mark.parametrize("optimizer", [optax.sgd(0.1), optax.adam(1e-2)],
                         ids=["sgd", "adam"])
def test_vocab_sharded_embedding_matches_single_device(builder, optimizer):
    trainable = make_trainable(optimizer)
    runner = AutoDist({}, builder()).build(trainable)
    assert runner.lowered.plan.var_plans["embedding"].sparse_lookup

    batches = [make_batch(s) for s in range(3)]
    for b in batches:
        runner.step(b)
    got = runner.get_params()
    want = single_device_reference(make_trainable(optimizer), batches)
    for name in ("embedding", "head"):
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(got[name])[0]),
            np.asarray(jax.tree.leaves(want[name])[0]),
            rtol=2e-6, atol=2e-6, err_msg=name)


def test_no_full_table_collectives_in_hlo():
    """The compiled step must not all-gather (or all-reduce) the padded
    table — only batch-sized index/row collectives (≙ the reference's
    'touched rows only' Parallax guarantee)."""
    vocab = 4096  # unambiguous dim to grep for in the HLO
    trainable = make_trainable(vocab=vocab)
    runner = AutoDist({}, Parallax()).build(trainable)
    batch = runner._place_batch(make_batch(vocab=vocab))
    lowered = runner.lowered.step_fn.lower(runner.state, batch,
                                           jax.random.PRNGKey(0))
    hlo = lowered.compiler_ir("hlo").as_hlo_text()
    bad = [ln for ln in hlo.splitlines()
           if re.search(r"all-(gather|reduce)", ln)
           and re.search(rf"\b{vocab},{DIM}\b|\b{vocab},\s*{DIM}\b", ln)]
    assert not bad, f"full-table collectives found:\n" + "\n".join(bad)


def test_duplicate_and_hot_rows_accumulate():
    """Every device hitting the same row must sum its contribution."""
    trainable = make_trainable()
    runner = AutoDist({}, Parallax()).build(trainable)
    ids = np.zeros((BATCH, SEQ), np.int32)  # all lookups hit row 0
    b = {"ids": ids, "y": np.ones(BATCH, np.float32)}
    runner.step(b)
    got = runner.get_params()
    want = single_device_reference(make_trainable(), [b])
    np.testing.assert_allclose(np.asarray(got["embedding"]),
                               np.asarray(want["embedding"]),
                               rtol=2e-6, atol=2e-6)


def test_dense_fallback_via_jax_array():
    """Non-lookup consumers of a vocab-sharded table (e.g. a tied decode
    matmul) must still work, via the dense all_gather escape hatch."""
    rng = np.random.RandomState(0)
    params = {"embedding": jnp.asarray(rng.randn(VOCAB, DIM) * 0.1,
                                       jnp.float32)}

    def loss_fn(p, batch):
        emb = embedding_lookup(p["embedding"], batch["ids"]).mean(axis=1)
        logits = emb @ jnp.asarray(p["embedding"]).T  # dense use of table
        return -jnp.mean(jax.nn.log_softmax(logits)[:, 0])

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                       sparse_params=("embedding",))
    runner = AutoDist({}, Parallax()).build(trainable)
    batches = [make_batch(s) for s in range(2)]
    for b in batches:
        runner.step(b)
    got = runner.get_params()
    want = single_device_reference(
        Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                               sparse_params=("embedding",)), batches)
    np.testing.assert_allclose(np.asarray(got["embedding"]),
                               np.asarray(want["embedding"]),
                               rtol=2e-6, atol=2e-6)


def test_flax_embed_params_survive_wrapper():
    """flax ``nn.Embed`` over a vocab-sharded table: jnp.take should hit
    the ``__jax_array__`` fallback and train correctly."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, ids):
            x = nn.Embed(VOCAB, DIM, name="embed")(ids).mean(axis=1)
            return nn.Dense(1, name="out")(x)[:, 0]

    model = Tiny()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, SEQ), jnp.int32))["params"]

    def loss_fn(p, batch):
        return jnp.mean((model.apply({"params": p}, batch["ids"])
                         - batch["y"]) ** 2)

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1),
                                       sparse_params=("embed/embedding",))
    runner = AutoDist({}, Parallax()).build(trainable)
    b = make_batch()
    m0 = float(np.asarray(runner.step(b)["loss"]))
    m1 = float(np.asarray(runner.step(b)["loss"]))
    assert np.isfinite(m0) and np.isfinite(m1) and m1 < m0
