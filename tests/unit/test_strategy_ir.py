"""Strategy IR tests (≙ reference ``test_strategy_base.py``: strategy
serialization round-trip + builder outputs)."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import ResourceSpec, Trainable
from autodist_tpu.strategy import builders
from autodist_tpu.strategy.ir import (AllReduceSynchronizer, NodeConfig,
                                      PartitionerConfig, PSSynchronizer,
                                      Strategy)


def make_trainable():
    params = {
        "embed": {"table": jnp.zeros((16384, 8), jnp.float32)},  # sparse
        "dense": {"w": jnp.zeros((8, 4), jnp.float32),
                  "b": jnp.zeros((4,), jnp.float32)},
    }
    return Trainable.from_loss_fn(lambda p, b: 0.0, params, optax.sgd(0.1))


RS = lambda: ResourceSpec({})


def test_roundtrip(tmp_path):
    s = Strategy(node_configs=[
        NodeConfig("a/w", AllReduceSynchronizer(compressor="fp16", group=2)),
        NodeConfig("b/t", PSSynchronizer(sync=True, staleness=1),
                   partitioner=PartitionerConfig("4,1"), is_sparse=True),
    ])
    path = s.serialize(str(tmp_path / "strat"))
    s2 = Strategy.from_json(open(path).read())
    assert s2.id == s.id
    assert s2.node_configs[0].synchronizer.compressor == "fp16"
    assert s2.node_configs[1].partitioner.partition_str == "4,1"
    assert s2.node_configs[1].partitioner.split_axis == 0
    assert s2.node_configs[1].is_sparse


def test_partitioner_config_validation():
    assert PartitionerConfig("1,4,1").split_axis == 1
    assert PartitionerConfig("1,4,1").num_shards == 4
    assert PartitionerConfig("").num_shards == 1
    with pytest.raises(ValueError):
        PartitionerConfig("2,4").split_axis


def test_sparse_detection():
    infos = {i.name: i for i in make_trainable().var_infos()}
    assert infos["embed/table"].is_sparse
    assert not infos["dense/w"].is_sparse


@pytest.mark.parametrize("name", sorted(builders.BUILDERS))
def test_builder_covers_all_vars(name):
    t = make_trainable()
    s = builders.create(name).build(t, RS())
    assert {n.var_name for n in s.node_configs} == set(t.var_names())
    assert s.graph_config.replicas == 8
    # round-trip every builder's output
    s2 = Strategy.from_json(s.to_json())
    assert [n.var_name for n in s2.node_configs] == \
        [n.var_name for n in s.node_configs]


def test_parallax_routes_sparse_to_ps():
    s = builders.Parallax().build(make_trainable(), RS())
    by_name = {n.var_name: n for n in s.node_configs}
    assert by_name["embed/table"].synchronizer.kind == "ps"
    assert by_name["embed/table"].partitioner.num_shards == 8
    assert by_name["dense/w"].synchronizer.kind == "allreduce"


def test_allreduce_grouping():
    s = builders.AllReduce(chunk_size=2).build(make_trainable(), RS())
    groups = [n.synchronizer.group for n in s.node_configs]
    assert groups == [0, 0, 1]


def test_lb_assignment_balances():
    # biggest var must not share a bin when bins >= vars
    from autodist_tpu.strategy.base import greedy_assign
    t = make_trainable()
    assignment = greedy_assign(t.var_infos(), 2)
    assert set(assignment.values()) <= {0, 1}
    # the large embedding alone in its bin
    embed_bin = assignment["embed/table"]
    others = [v for k, v in assignment.items() if k != "embed/table"]
    assert all(b != embed_bin for b in others)


def test_unknown_builder_raises():
    with pytest.raises(ValueError):
        builders.create("Nope")
