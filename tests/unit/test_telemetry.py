"""Unified telemetry subsystem (telemetry/): spans, metrics registry,
per-step records, run manifest, drift report, and the report tool.

Tier-1 by design: the acceptance contract is that one CPU-mesh run of
``examples/pipeline_train.py --telemetry-dir`` yields a valid chrome
trace, a metrics JSONL with per-step records, a run manifest, and a
predicted-vs-measured drift report — asserted here, so a schema break
fails CI without hardware.
"""
import json
import logging as py_logging
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, ResourceSpec, Trainable, fit
from autodist_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def make_trainable(seed=0):
    k = jax.random.PRNGKey(seed)
    params = {"w": jax.random.normal(k, (32, 8)) * 0.1}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.adamw(1e-2))


def source(step):
    r = np.random.RandomState(step)
    return {"x": r.randn(16, 32).astype(np.float32),
            "y": r.randn(16, 8).astype(np.float32)}


# --------------------------------------------------------------------- #
# spans + chrome trace
# --------------------------------------------------------------------- #
def test_span_nesting_and_chrome_trace(tmp_path):
    with telemetry.span("outer", phase="x"):
        with telemetry.span("inner"):
            time.sleep(0.002)
    paths = telemetry.flush(str(tmp_path))
    with open(paths["trace"]) as f:
        trace = json.load(f)
    assert "traceEvents" in trace
    events = {e["name"]: e for e in trace["traceEvents"]}
    assert {"outer", "inner"} <= set(events)
    for e in trace["traceEvents"]:
        # chrome-trace complete events: ph "X", microsecond ts + dur
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert e["pid"] == os.getpid()
    outer, inner = events["outer"], events["inner"]
    # nesting: the inner interval lies inside the outer one (1 µs slack
    # for float rounding)
    assert outer["ts"] <= inner["ts"] + 1.0
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    assert inner["dur"] >= 2000  # the 2 ms sleep, in µs
    assert outer["args"]["phase"] == "x"
    assert inner["args"]["depth"] == 1


def test_span_set_attributes():
    with telemetry.span("s") as sp:
        sp.set(lowering="pipeline")
    [event] = telemetry.get().chrome_trace()["traceEvents"]
    assert event["args"]["lowering"] == "pipeline"


# --------------------------------------------------------------------- #
# metrics registry
# --------------------------------------------------------------------- #
def test_metrics_registry_flush(tmp_path):
    telemetry.counter("a/count").inc()
    telemetry.counter("a/count").inc(2)
    telemetry.gauge("a/gauge").set(2.5)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        telemetry.histogram("a/hist").observe(v)
    paths = telemetry.flush(str(tmp_path))
    with open(paths["metrics"]) as f:
        recs = [json.loads(line) for line in f]
    by_name = {r["name"]: r for r in recs if "name" in r}
    assert by_name["a/count"]["kind"] == "counter"
    assert by_name["a/count"]["value"] == 3
    assert by_name["a/gauge"]["value"] == 2.5
    hist = by_name["a/hist"]
    assert hist["count"] == 5 and hist["p50"] == 3.0 and hist["mean"] == 3.0


def test_metric_kind_conflict_rejected():
    telemetry.counter("x")
    with pytest.raises(TypeError):
        telemetry.gauge("x")


# --------------------------------------------------------------------- #
# per-step records + sampling + manifest
# --------------------------------------------------------------------- #
def test_step_records_and_sampling(tmp_path):
    telemetry.configure(sample=2)
    for i in range(10):
        telemetry.record_step(step=i, duration_s=0.001 * (i + 1),
                              examples=32)
    recs = telemetry.get().step_records()
    assert len(recs) == 5  # every 2nd kept
    assert [r["step"] for r in recs] == [0, 2, 4, 6, 8]
    assert all(r["kind"] == "step" and r["examples"] == 32 for r in recs)
    paths = telemetry.flush(str(tmp_path))
    with open(paths["manifest"]) as f:
        manifest = json.load(f)
    assert manifest["kind"] == "manifest"
    assert manifest["telemetry"]["steps_seen"] == 10
    assert manifest["telemetry"]["step_records"] == 5
    # provenance rides every manifest: this repo's HEAD sha
    assert len(manifest["provenance"]["git_sha"]) == 40
    assert manifest["provenance"]["jax"] == jax.__version__


# --------------------------------------------------------------------- #
# disabled path: no files, no wrapper objects
# --------------------------------------------------------------------- #
def test_disabled_no_files_no_wrappers(tmp_path):
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("AUTODIST_TPU_TELEMETRY", "0")
        telemetry.reset()
        assert not telemetry.enabled()
        # span() and the instruments return the SAME shared no-op
        # singletons — the disabled path allocates nothing per call
        assert telemetry.span("a") is telemetry.span("b")
        assert telemetry.span("a") is telemetry.NULL_SPAN
        assert telemetry.counter("c") is telemetry.NULL_INSTRUMENT
        assert telemetry.histogram("h") is telemetry.NULL_INSTRUMENT
        with telemetry.span("region"):
            telemetry.counter("c").inc()
        assert telemetry.record_step(step=0, duration_s=0.1) is False
        # flush writes nothing, even with an explicit directory
        assert telemetry.flush(str(tmp_path)) == {}
        assert os.listdir(tmp_path) == []
    telemetry.reset()
    assert telemetry.enabled()


@pytest.mark.parametrize("val", ["0", "false", "FALSE", "no", "off"])
def test_disabled_env_spellings(val):
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("AUTODIST_TPU_TELEMETRY", val)
        assert not telemetry.reset().enabled
    telemetry.reset()


# --------------------------------------------------------------------- #
# instrumented real paths
# --------------------------------------------------------------------- #
def test_runner_run_summary_and_records():
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    runner.run([source(i) for i in range(4)], num_steps=4)
    s = runner.summary()
    assert s["steps"] == 4
    assert s["mean_ms"] > 0 and s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
    assert s["examples_per_sec"] > 0
    recs = telemetry.get().step_records()
    assert len(recs) == 4
    assert all(r["examples"] == 16 for r in recs)
    assert telemetry.counter("runner/steps").value == 4


def test_fit_step_records_match_steps_run():
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    fit(runner, source, steps=5, log_every=0)
    assert runner.step_count == 5
    recs = telemetry.get().step_records()
    assert sum(r.get("steps", 1) for r in recs) == 5
    # the build path and fit both left spans
    names = {e["name"]
             for e in telemetry.get().chrome_trace()["traceEvents"]}
    assert {"autodist/build", "autodist/lower", "train/fit"} <= names


def test_fit_fused_records_cover_every_step():
    runner = AutoDist({}, AllReduce()).build(make_trainable())
    fit(runner, source, steps=6, log_every=0, steps_per_loop=4)
    assert runner.step_count == 6
    recs = telemetry.get().step_records()
    assert sum(r.get("steps", 1) for r in recs) == 6


# --------------------------------------------------------------------- #
# drift report
# --------------------------------------------------------------------- #
def test_drift_report_synthetic_pair(tmp_path):
    from autodist_tpu.simulator.cost_model import StrategyCost

    predicted = StrategyCost(comm_bytes=1e6, comm_time_s=0.002,
                             num_collectives=4, mem_bytes_per_device=1e9,
                             feasible=True, overlap_time_s=0.0005)
    measured = {"step": {"p50_ms": 4.0, "p99_ms": 5.0, "steps": 10},
                "memory": {"bytes_in_use": 2_000_000_000}}
    report = telemetry.drift_report(predicted=predicted, measured=measured,
                                    out_dir=str(tmp_path))
    assert report["ratios"]["step_time"] == pytest.approx(2.0)
    assert report["ratios"]["memory"] == pytest.approx(2.0)
    # per-term split: blocking comm vs exposed overlap
    assert report["predicted"]["comm_time_s"] == pytest.approx(0.0015)
    assert report["predicted"]["exposed_overlap_s"] == pytest.approx(0.0005)
    assert report["predicted"]["comm_only"] is True
    assert report["measured"]["mem_bytes_per_device"] == 2_000_000_000
    assert report["measured"]["memory_source"] == "device_bytes_in_use"
    with open(os.path.join(tmp_path, "drift.json")) as f:
        assert json.load(f)["kind"] == "drift"


def test_drift_report_real_strategy_proposes_link_constants():
    trainable = make_trainable()
    rs = ResourceSpec({})
    strategy = AllReduce().build(trainable, rs)
    from autodist_tpu.simulator.cost_model import CostModel

    cm = CostModel(rs)
    # measured far slower than the analytic prediction -> the report
    # proposes a lower effective ici_gbps for calibration.json
    report = telemetry.drift_report(
        strategy, cm, {"step": {"p50_ms": 10.0, "steps": 8}},
        trainable=trainable)
    assert report["strategy"]["id"] == strategy.id
    assert report["ratios"]["step_time"] > 1.0
    proposal = report["proposal"]
    assert proposal and "link" in proposal
    assert 0 < proposal["link"]["ici_gbps"] < cm.chip.ici_gbps
    # memory join falls back to host RSS on a CPU mesh, flagged as such
    assert report["measured"]["memory_source"] == "host_rss_peak"
    # ratio gauges land in the registry for the JSONL sink
    assert telemetry.gauge("drift/step_time_ratio").value \
        == pytest.approx(report["ratios"]["step_time"])


def test_drift_report_requires_inputs():
    with pytest.raises(ValueError):
        telemetry.drift_report(measured={"step": {"p50_ms": 1.0}})


# --------------------------------------------------------------------- #
# logging satellites
# --------------------------------------------------------------------- #
def test_set_verbosity_reaches_handlers():
    from autodist_tpu.utils import logging as adlog

    logger = adlog.get_logger()
    try:
        for h in logger.handlers:
            h.setLevel(py_logging.ERROR)
        adlog.set_verbosity(py_logging.DEBUG)
        assert logger.level == py_logging.DEBUG
        assert all(h.level == py_logging.DEBUG for h in logger.handlers)
    finally:
        adlog.set_verbosity(py_logging.INFO)


def test_log_file_name_is_per_run():
    from autodist_tpu.utils import logging as adlog

    logger = adlog.get_logger()
    file_handlers = [h for h in logger.handlers
                     if isinstance(h, py_logging.FileHandler)]
    if not file_handlers:  # read-only fs: console-only logging
        pytest.skip("no file handler on this fs")
    base = os.path.basename(file_handlers[0].baseFilename)
    # <pid>-<timestamp>.log: concurrent workers cannot collide on the
    # same epoch-second name
    assert base.startswith(f"{os.getpid()}-")


# --------------------------------------------------------------------- #
# acceptance: pipeline_train --telemetry-dir + report tool (CI smoke)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def pipeline_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("pp_telemetry")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO,
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/pipeline_train.py"),
         "--steps", "6", "--stages", "2", "--hidden", "16", "--batch", "8",
         "--microbatches", "2", "--telemetry-dir", str(out)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return out


def test_pipeline_train_telemetry_acceptance(pipeline_run):
    out = pipeline_run
    # chrome trace with the build-path spans
    with open(out / "trace.json") as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"autodist/build_or_load_strategy", "autodist/build",
            "autodist/lower"} <= names
    assert all(e["ph"] == "X" for e in trace["traceEvents"])
    # metrics JSONL with one record per step
    with open(out / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 6
    assert all(r["duration_ms"] > 0 and r["examples"] == 8 for r in steps)
    counters = {r["name"]: r["value"] for r in recs
                if r["kind"] == "counter"}
    assert counters.get("runner/steps") == 6
    # run manifest: provenance + the run's parallelism config
    with open(out / "manifest.json") as f:
        manifest = json.load(f)
    assert manifest["provenance"]["git_sha"]
    assert manifest["run"]["microbatches"] == 2
    assert manifest["run"]["step_summary"]["p50_ms"] > 0
    # drift report: the predicted-vs-measured join covers step time AND
    # memory
    with open(out / "drift.json") as f:
        drift = json.load(f)
    assert drift["kind"] == "drift"
    assert drift["strategy"]["lowering"] == "pipeline"
    assert "step_time" in drift["ratios"] and "memory" in drift["ratios"]
    assert drift["predicted"]["mem_bytes_per_device"] > 0
    assert drift["measured"]["step_time_s"] > 0


def test_telemetry_report_tool_renders_and_checks(pipeline_run):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    # schema smoke (the CI gate): a valid run passes --check
    assert telemetry_report.main([str(pipeline_run), "--check"]) == 0
    md = telemetry_report.render(str(pipeline_run))
    assert "## steps" in md and "p50" in md
    assert "## drift (measured / predicted)" in md
    assert "git:" in md


def test_telemetry_report_tool_fails_on_schema_break(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    with open(tmp_path / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"kind": "step"}) + "\n")       # missing fields
        f.write(json.dumps({"kind": "wat", "x": 1}) + "\n")  # unknown kind
    assert telemetry_report.main([str(tmp_path), "--check"]) == 2
