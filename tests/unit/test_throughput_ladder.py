"""Throughput-ladder goldens (ISSUE 16): chunked prefill, CoW prefix
caching, speculative decoding.

The correctness bar is *exactness*: every rung is a pure throughput
transform, so each one must reproduce the vanilla engine's token
stream bit-for-bit — chunked prefill vs single-shot (tp∈{1,2} ×
vocab-parallel, including a chunk that does not divide the prompt),
a shared-prefix warm admission vs a cold cache, and speculative
decode vs plain decode for greedy AND seeded sampling (same-weights
and different-weights drafts).  Around the streams: the refcounted
allocator's ``free + used == total`` invariant after every terminal
state (including router failover and a cancelled hedge loser), the
coded ``PromptBudgetError`` both ways, the ADT116/ADT117 block-trace
lint clean on honest engine traces, and the cost-model ladder pins
both ways.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.serving import (ContinuousBatcher, FleetConfig,
                                  PromptBudgetError, Router,
                                  ServingEngine, ServingFleet)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V = 33          # odd: V % 2 != 0 exercises the vocab zero-pad path
MAX_LEN = 24
PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]   # 10 tokens: chunk=4 -> 3 chunks
MAX_NEW = 6


def make_cfg(vocab=V, max_len=MAX_LEN):
    return TransformerConfig(
        vocab_size=vocab, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_len=max_len, dtype=jnp.float32,
        dropout_rate=0.0, attention_dropout_rate=0.0)


@pytest.fixture(scope="module")
def cfg():
    return make_cfg()


@pytest.fixture(scope="module")
def params(cfg):
    return make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params


@pytest.fixture(scope="module")
def draft_params(cfg):
    """A draft with *different* weights: speculation must stay exact
    even when the draft proposes wrong tokens (acceptance < 1)."""
    return make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(3)).params


def make_engine(cfg, params, **kw):
    base = dict(num_slots=2, max_len=MAX_LEN, prefill_len=12,
                decode_steps=3, kv_layout="paged", kv_block_len=4)
    base.update(kw)
    return ServingEngine(cfg, params, **base)


def run_single(engine, prompt, n, seed=None, slot=0):
    """Drive one request through the raw engine API and return its
    first ``n`` tokens (the golden-comparison harness)."""
    B = engine.num_slots
    P = engine.max_prompt_tokens if engine.prefill_chunk \
        else engine.prefill_len
    prompts = np.zeros((B, P), np.int64)
    prompts[slot, :len(prompt)] = prompt
    p_lens = np.zeros((B,), np.int64)
    p_lens[slot] = len(prompt)
    admit = np.zeros((B,), bool)
    admit[slot] = True
    seeds = None if seed is None else np.full((B,), seed, np.int32)
    engine.reserve_slot(slot, len(prompt), n, prompt=np.asarray(prompt))
    tok = engine.prefill(prompts, p_lens, admit, seeds=seeds)
    out = [int(tok[slot])]
    active = admit.copy()
    while len(out) < n:
        w = engine.decode_window(active)
        out.extend(int(t) for t in w.tokens[:w.counts[slot], slot])
    engine.release_slot(slot)
    return out[:n]


def assert_idle_accounting(engine):
    free, used, total = engine.block_accounting()
    assert used == 0 and free == total, (free, used, total)


# --------------------------------------------------------------------- #
# rung 1: chunked prefill == single-shot, token for token
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("tp,vocab_parallel",
                         [(1, False), (2, False), (2, True)])
def test_chunked_prefill_matches_single_shot(cfg, params, tp,
                                             vocab_parallel):
    """Chunk-by-chunk prefill (chunk=4 over a 10-token prompt — the
    final chunk is partial) emits the identical stream as one
    prefill dispatch, across tp and the vocab-parallel loss head."""
    kw = dict(tensor_parallel=tp, vocab_parallel=vocab_parallel)
    base = run_single(make_engine(cfg, params, **kw), PROMPT, MAX_NEW)
    chunked = make_engine(cfg, params, prefill_chunk=4, **kw)
    got = run_single(chunked, PROMPT, MAX_NEW)
    assert got == base, (got, base)
    assert chunked.last_prefill_chunks == 3   # ceil(10 / 4)
    assert_idle_accounting(chunked)


def test_chunked_prefill_lifts_the_prompt_bucket(cfg, params):
    """Single-shot admission buckets prompts at ``prefill_len``;
    chunking lifts the bucket to the whole context window."""
    plain = make_engine(cfg, params, prefill_len=8)
    assert plain.max_prompt_tokens == 8
    chunked = make_engine(cfg, params, prefill_len=8, prefill_chunk=4)
    assert chunked.max_prompt_tokens > 8
    long_prompt = list(range(1, 15))          # 14 tokens > bucket of 8
    got = run_single(chunked, long_prompt, MAX_NEW)
    wide = make_engine(cfg, params, prefill_len=16)
    assert got == run_single(wide, long_prompt, MAX_NEW)


def test_flash_prefill_kernel_matches_composed_path(cfg, params):
    """The fused paged flash-prefill kernel is numerics-identical to
    the composed gather+attention chunk path (greedy golden)."""
    base = run_single(make_engine(cfg, params, prefill_chunk=4),
                      PROMPT, MAX_NEW)
    kern = make_engine(cfg, params, prefill_chunk=4,
                       kernel=("flash_prefill",))
    assert run_single(kern, PROMPT, MAX_NEW) == base


# --------------------------------------------------------------------- #
# rung 2: CoW prefix caching — warm == cold, bit for bit
# --------------------------------------------------------------------- #
def test_prefix_cache_shared_equals_cold(cfg, params):
    """A second request sharing a resident prefix decodes the exact
    stream a cold cache gives it, its admission charges only the
    novel suffix (2 full blocks + partial tail hit), and releasing
    both requests restores ``free == total``."""
    base = run_single(make_engine(cfg, params), PROMPT, MAX_NEW)
    e = make_engine(cfg, params, prefill_chunk=4, prefix_caching=True)
    assert run_single(e, PROMPT, MAX_NEW) == base   # cold == vanilla

    # hold slot 0 resident, then admit the same prompt into slot 1
    e.reserve_slot(0, len(PROMPT), MAX_NEW, prompt=np.asarray(PROMPT))
    prompts = np.zeros((2, e.max_prompt_tokens), np.int64)
    prompts[0, :len(PROMPT)] = PROMPT
    e.prefill(prompts, np.array([len(PROMPT), 0]),
              np.array([True, False]))
    hits = e.reserve_slot(1, len(PROMPT), MAX_NEW,
                          prompt=np.asarray(PROMPT))
    assert hits == 3        # 10-token prompt @ block 4: 2 full + tail
    prompts[1] = prompts[0]
    e.prefill(prompts, np.array([0, len(PROMPT)]),
              np.array([False, True]))
    w = e.decode_window(np.array([True, True]))
    for slot in (0, 1):
        got = [int(t) for t in w.tokens[:w.counts[slot], slot]]
        assert got == base[1:1 + len(got)], (slot, got)
    e.release_slot(0)
    e.release_slot(1)
    assert_idle_accounting(e)


def test_prefix_cache_admits_strictly_more_at_equal_pool(cfg, params):
    """The capacity claim at the heart of the rung: at the same pool,
    admitting a second shared-prefix request leaves strictly more
    free blocks with caching on than off."""
    def admit_two(prefix_caching):
        e = make_engine(cfg, params, prefill_chunk=4,
                        prefix_caching=prefix_caching)
        prompts = np.zeros((2, e.max_prompt_tokens), np.int64)
        prompts[0, :len(PROMPT)] = PROMPT
        e.reserve_slot(0, len(PROMPT), MAX_NEW,
                       prompt=np.asarray(PROMPT))
        e.prefill(prompts, np.array([len(PROMPT), 0]),
                  np.array([True, False]))
        e.reserve_slot(1, len(PROMPT), MAX_NEW,
                       prompt=np.asarray(PROMPT))
        return e.free_blocks
    assert admit_two(True) > admit_two(False)


def test_lint_block_trace_clean_on_real_engine_events(cfg, params):
    """The honest engine's own allocator trace — through sharing, CoW
    and release — replays clean under the ADT116/ADT117 rules, and a
    doctored double-free in the same trace fires ADT117."""
    from autodist_tpu.analysis import lint_block_trace

    e = make_engine(cfg, params, prefill_chunk=4, prefix_caching=True)
    run_single(e, PROMPT, MAX_NEW)
    e.reserve_slot(0, len(PROMPT), MAX_NEW, prompt=np.asarray(PROMPT))
    prompts = np.zeros((2, e.max_prompt_tokens), np.int64)
    prompts[0, :len(PROMPT)] = PROMPT
    e.prefill(prompts, np.array([len(PROMPT), 0]),
              np.array([True, False]))
    e.reserve_slot(1, len(PROMPT), MAX_NEW, prompt=np.asarray(PROMPT))
    prompts[1] = prompts[0]
    e.prefill(prompts, np.array([0, len(PROMPT)]),
              np.array([False, True]))
    e.decode_window(np.array([True, True]))
    e.release_slot(0)
    e.release_slot(1)
    trace = list(e._allocator.events)
    assert any(ev[0] == "share" for ev in trace)   # sharing happened
    report = lint_block_trace(trace)
    assert not report.diagnostics, report.render()

    freed = next(b for op, b in reversed(
        [ev[:2] for ev in trace if ev[0] in ("alloc", "free")])
        if op == "free")
    doctored = trace + [("free", freed)]
    codes = {d.code for d in lint_block_trace(doctored).diagnostics}
    assert "ADT117" in codes


# --------------------------------------------------------------------- #
# rung 3: speculative decode == vanilla, greedy and sampled
# --------------------------------------------------------------------- #
def test_speculative_matches_vanilla_greedy(cfg, params, draft_params):
    """Draft-propose/verify decode reproduces plain greedy decode
    token for token — whether the draft agrees (same weights,
    acceptance ~1) or mispredicts (different weights) — and both the
    verify engine's and the nested draft's pools drain to zero."""
    base = run_single(make_engine(cfg, params), PROMPT, MAX_NEW)
    for dparams in (params, draft_params):
        e = make_engine(cfg, params, speculative=2, draft_cfg=cfg,
                        draft_params=dparams)
        got = run_single(e, PROMPT, MAX_NEW)
        assert got == base, (got, base)
        assert_idle_accounting(e)
        assert_idle_accounting(e.draft)


def test_sampled_parity_across_all_rungs(cfg, params, draft_params):
    """Seeded sampling (temperature 0.9) holds the same exactness:
    the position-keyed gumbel draw makes chunked prefill, the flash
    kernel, and speculative decode (same- and different-weights
    drafts) reproduce the vanilla sampled stream draw for draw."""
    kw = dict(temperature=0.9, top_k=0)
    base = run_single(make_engine(cfg, params, **kw), PROMPT, MAX_NEW,
                      seed=7)
    variants = [
        make_engine(cfg, params, prefill_chunk=4, **kw),
        make_engine(cfg, params, prefill_chunk=4,
                    kernel=("flash_prefill",), **kw),
        make_engine(cfg, params, speculative=2, draft_cfg=cfg,
                    draft_params=params, **kw),
        make_engine(cfg, params, speculative=2, draft_cfg=cfg,
                    draft_params=draft_params, **kw),
    ]
    for e in variants:
        got = run_single(e, PROMPT, MAX_NEW, seed=7)
        assert got == base, (got, base)
        assert_idle_accounting(e)


# --------------------------------------------------------------------- #
# the rungs under continuous batching, routing and failure
# --------------------------------------------------------------------- #
def make_factory(cfg, params, draft_params=None):
    def factory():
        kw = dict(prefill_chunk=4, prefix_caching=True)
        if draft_params is not None:
            kw.update(speculative=2, draft_cfg=cfg,
                      draft_params=draft_params)
        return make_engine(cfg, params, **kw)
    return factory


def test_interleaved_equals_run_alone_on_ladder_engine(cfg, params):
    """Continuous batching over the full ladder engine: interleaved
    shared-prefix requests with staggered budgets each get exactly
    their run-alone stream, completions carry the ladder facts, and
    the pool drains to zero."""
    factory = make_factory(cfg, params)
    reqs = [(PROMPT, 6), (PROMPT, 4), (PROMPT[:6] + [7, 7], 5),
            (PROMPT, 3)]
    golden = {}
    alone = ContinuousBatcher(make_factory(cfg, params)())
    for i, (p, n) in enumerate(reqs):
        rid = alone.submit(p, max_new_tokens=n)
        golden[i] = alone.run()[rid].tokens

    b = ContinuousBatcher(factory())
    rids = [b.submit(p, max_new_tokens=n) for p, n in reqs]
    done = b.run()
    hit_total = 0
    for i, rid in enumerate(rids):
        comp = done[rid]
        assert comp.tokens == golden[i], (i, comp.tokens, golden[i])
        assert comp.prefill_chunks >= 2     # every prompt was chunked
        hit_total += comp.prefix_hit_blocks
    assert hit_total > 0, "no admission ever shared a resident prefix"
    assert_idle_accounting(b.engine)


def test_router_prompt_budget_both_paths(cfg, params):
    """A prompt beyond the single-shot bucket is a *coded* rejection
    (``PromptBudgetError``, ``serve/prompt_budget``) — and the same
    prompt on a chunked fleet is a first-class admission."""
    long_prompt = list(range(1, 15))          # 14 > prefill_len=12

    def plain_factory():
        return make_engine(cfg, params)       # no chunking: bucket 12
    router = Router(ServingFleet(plain_factory, replicas=1))
    with pytest.raises(PromptBudgetError) as err:
        router.submit(long_prompt, max_new_tokens=MAX_NEW)
    assert PromptBudgetError.code == "serve/prompt_budget"
    assert PromptBudgetError.code in str(err.value)
    assert "chunk" in str(err.value)          # names the fix

    golden = run_single(make_engine(cfg, params, prefill_chunk=4),
                        long_prompt, MAX_NEW)
    fleet = ServingFleet(make_factory(cfg, params), replicas=1)
    router2 = Router(fleet)
    rid = router2.submit(long_prompt, max_new_tokens=MAX_NEW)
    done = router2.run()
    assert done[rid].tokens == golden
    for _, (free, used, total) in fleet.block_accounting().items():
        assert used == 0 and free == total


def test_failover_midstream_keeps_ladder_parity(cfg, params):
    """A replica crash mid-stream on the chunked+prefix-caching fleet:
    failover re-prefills (chunked, possibly sharing survivors'
    prefixes) and still completes every request with its run-alone
    stream — with zero block residency on every replica after."""
    factory = make_factory(cfg, params)
    reqs = [(PROMPT, 0), (PROMPT[:6] + [7, 7], 0), (PROMPT, 0)]
    golden = {}
    alone = ContinuousBatcher(factory())
    for i, (p, _) in enumerate(reqs):
        rid = alone.submit(p, max_new_tokens=MAX_NEW)
        golden[i] = alone.run()[rid].tokens

    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p, _ in reqs]
    router.step()                             # requests mid-stream
    fleet.inject("replica-0", "crash")
    done = router.run()
    failovers = 0
    for i, rid in enumerate(rids):
        assert done[rid].tokens == golden[i], (i, done[rid])
        failovers += done[rid].failovers
    assert failovers >= 1, "the crash never exercised failover"
    for _, (free, used, total) in fleet.block_accounting().items():
        assert used == 0 and free == total


def test_hedge_loser_cancellation_returns_shared_blocks(cfg, params):
    """The hedging terminal on the ladder engine: the loser's
    cancellation must unwind refcounted (possibly shared) blocks,
    not just plain ones — ``free == total`` on both replicas."""
    factory = make_factory(cfg, params)
    alone = ContinuousBatcher(factory())
    rid0 = alone.submit(PROMPT, max_new_tokens=MAX_NEW)
    golden = alone.run()[rid0].tokens

    fleet = ServingFleet(factory, replicas=2,
                         config=FleetConfig(hedge_timeout_s=0.02))
    router = Router(fleet)
    fleet.inject("replica-0", "slow", duration_s=5.0)
    rid = router.submit(PROMPT, max_new_tokens=MAX_NEW)
    done = router.run()
    comp = done[rid]
    assert comp.tokens == golden
    assert comp.hedged and comp.hedge_won
    slow = fleet.replicas[0]
    cancelled = [c for c in slow.batcher.completions.values()
                 if c.finish_reason == "cancelled"]
    assert cancelled, "the hedge loser was never cancelled"
    for _, (free, used, total) in fleet.block_accounting().items():
        assert used == 0 and free == total


# --------------------------------------------------------------------- #
# telemetry: the ladder facts are schema-gated serve fields
# --------------------------------------------------------------------- #
def test_ladder_serve_records_schema_and_report(cfg, params,
                                               draft_params, tmp_path):
    telemetry.reset()
    telemetry.configure(out_dir=str(tmp_path), enabled=True)
    try:
        b = ContinuousBatcher(make_factory(cfg, params, draft_params)())
        rids = [b.submit(PROMPT, max_new_tokens=4),
                b.submit(PROMPT, max_new_tokens=3)]
        b.run()
        telemetry.flush()
    finally:
        telemetry.reset()
    with open(os.path.join(tmp_path, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    serves = {r["request"]: r for r in recs if r.get("kind") == "serve"}
    assert set(serves) == set(rids)
    for rec in serves.values():
        assert rec["prefill_chunks"] >= 2
        assert rec["spec_proposed"] >= rec["spec_accepted"] >= 0
        assert rec["prefix_hit_blocks"] >= 0

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    assert telemetry_report.check_schema(str(tmp_path)) == []
    md = telemetry_report.render(str(tmp_path))
    assert "throughput ladder" in md

    # the gate rejects a serve record missing the ladder facts, and
    # one claiming more acceptances than proposals
    with open(os.path.join(tmp_path, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({
            "kind": "serve", "request": "x", "tokens": 1,
            "ttft_ms": 1.0, "tokens_per_sec": 1.0, "queue_wait_ms": 0.0,
            "decode_ms": 1.0, "inter_token_p50_ms": 1.0,
            "inter_token_p99_ms": 1.0, "finish_reason": "eos"}) + "\n")
    problems = telemetry_report.check_schema(str(tmp_path))
    assert any("prefix_hit_blocks" in p for p in problems)


# --------------------------------------------------------------------- #
# cost model: every rung priced both ways
# --------------------------------------------------------------------- #
def _trainable():
    return make_pipeline_lm_trainable(
        make_cfg(vocab=512, max_len=64), optax.sgd(0.1),
        jax.random.PRNGKey(0))


def _rs():
    from autodist_tpu.resource import ResourceSpec
    return ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 2}})


def test_decode_cost_prefix_caching_both_ways():
    from autodist_tpu.simulator import CostModel

    cm = CostModel(_rs())
    t = _trainable()
    paged = cm.decode_cost(t, {"tensor_parallel": 1,
                               "kv_layout": "paged"}, max_len=2048)
    hot = cm.decode_cost(t, {"tensor_parallel": 1, "kv_layout": "paged",
                             "prefix_caching": True},
                         max_len=2048, prefix_hit_rate=0.8)
    assert hot.request_capacity > paged.request_capacity
    assert hot.serve_score < paged.serve_score     # caching elected
    cold = cm.decode_cost(t, {"tensor_parallel": 1,
                              "kv_layout": "paged",
                              "prefix_caching": True}, max_len=2048)
    # zero hits: only the hash/refcount overhead remains -> rejected
    assert cold.serve_score > paged.serve_score
    assert cold.token_time_s > paged.token_time_s
    with pytest.raises(ValueError, match="paged"):
        cm.decode_cost(t, {"tensor_parallel": 1,
                           "prefix_caching": True}, max_len=2048)
    with pytest.raises(ValueError, match="prefix_hit_rate"):
        cm.decode_cost(t, {"tensor_parallel": 1, "kv_layout": "paged",
                           "prefix_caching": True},
                       max_len=2048, prefix_hit_rate=1.5)


def test_decode_cost_speculative_both_ways():
    from autodist_tpu.simulator import CostModel

    cm = CostModel(_rs())
    t = _trainable()
    vanilla = cm.decode_cost(t, {"tensor_parallel": 1,
                                 "kv_layout": "paged"}, max_len=2048)
    good = cm.decode_cost(t, {"tensor_parallel": 1,
                              "kv_layout": "paged", "speculative": 4},
                          max_len=2048, spec_acceptance=0.9)
    assert good.token_time_s < vanilla.token_time_s
    bad = cm.decode_cost(t, {"tensor_parallel": 1,
                             "kv_layout": "paged", "speculative": 4},
                         max_len=2048, spec_acceptance=0.1)
    assert bad.token_time_s > vanilla.token_time_s
    # the draft's residency taxes capacity regardless of acceptance
    assert good.request_capacity < vanilla.request_capacity
    with pytest.raises(ValueError, match="spec_acceptance"):
        cm.decode_cost(t, {"tensor_parallel": 1, "kv_layout": "paged",
                           "speculative": 4},
                       max_len=2048, spec_acceptance=2.0)


def test_rank_serving_ladder_is_opt_in():
    """The ladder zoo rungs appear only under ``ladder=True`` (the
    default zoo stays byte-stable), and under a hot shared-prefix
    traffic mix the capacity objective elects the caching rung."""
    from autodist_tpu.simulator import rank_serving
    from autodist_tpu.simulator.auto_strategy import \
        default_serving_candidates

    plain = default_serving_candidates(2)
    assert not any(c.get("prefix_caching") or c.get("speculative")
                   or c.get("prefill_chunk") for c in plain)
    zoo = default_serving_candidates(2, ladder=True)
    assert any(c.get("prefix_caching") for c in zoo)
    assert any(c.get("speculative") for c in zoo)
    assert any(c.get("prefill_chunk") and "flash_prefill"
               in tuple(c.get("kernel") or ()) for c in zoo)

    ranked = rank_serving(_trainable(), _rs(), objective="capacity",
                          mean_request_len=64.0, max_len=2048,
                          prefix_hit_rate=0.8, ladder=True)
    assert ranked[0][0].get("prefix_caching") is True
