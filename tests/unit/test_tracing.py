"""Distributed request tracing + the live telemetry plane (ISSUE 19).

The bar, in-process first (the cross-process half lives in
``test_remote_serving.py``): every request minted a ``trace_id`` at the
fleet edge carries it through dispatch records, replica prefill/decode
spans, disaggregated handoff records, and its completion; the stitcher
folds record shards into ONE chrome trace whose per-request timelines
read causally (fault → failover dispatch → re-prefill); the Router's
hedge calibration and the Autoscaler's TTFT trigger are VIEWS over the
same aggregator windows (identical percentile reads on identical
streams); the online drift monitor breaches edge-triggered in both
directions; and the report's new causal-chain gates fire on doctored
artifacts while staying silent on honest ones — including trace-id-less
pre-tracing records (back-compat).
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.serving import Router, ServingFleet, ServingEngine
from autodist_tpu.serving.autoscale import Autoscaler, AutoscaleConfig
from autodist_tpu.serving.disagg import DisaggServer
from autodist_tpu.serving.remote import tiny_engine_factory
from autodist_tpu.telemetry import (DriftMonitor, RollingWindow,
                                    TelemetryAggregator)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

import telemetry_report as tr  # noqa: E402

V, MAX_LEN, MAX_NEW = 33, 24, 6
PROMPTS = [[1, 2, 3], [4, 5], [6], [7, 8, 9, 10]]


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture(scope="module")
def factory():
    cfg = TransformerConfig(
        vocab_size=V, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_len=MAX_LEN, dtype=jnp.float32,
        dropout_rate=0.0, attention_dropout_rate=0.0)
    params = make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params

    def make():
        return ServingEngine(cfg, params, tensor_parallel=1,
                             num_slots=2, max_len=MAX_LEN,
                             prefill_len=16, decode_steps=3,
                             kv_layout="paged", kv_block_len=5)
    return make


# --------------------------------------------------------------------- #
# trace ids + ambient context
# --------------------------------------------------------------------- #
def test_mint_is_unique_and_context_tags_spans_and_records():
    a, b = telemetry.mint_trace_id(), telemetry.mint_trace_id()
    assert a != b and a.startswith("tr-")
    assert telemetry.current_trace_id() is None
    with telemetry.trace_context() as tid:
        assert telemetry.current_trace_id() == tid
        with telemetry.span("work"):
            pass
        telemetry.record_event("dispatch", request="r0", replica="x",
                               reason="route", re_emitted=0)
    assert telemetry.current_trace_id() is None
    ev = telemetry.get().chrome_trace()["traceEvents"][-1]
    assert ev["args"]["trace_id"] == tid
    rec = telemetry.get().step_records()[-1]
    assert rec["trace_id"] == tid
    assert isinstance(rec["ts_us"], float)   # the wall-anchored stamp


def test_explicit_trace_id_wins_over_ambient():
    with telemetry.trace_context("tr-ambient"):
        telemetry.record_event("serve", request="r", trace_id="tr-mine")
    assert telemetry.get().step_records()[-1]["trace_id"] == "tr-mine"


# --------------------------------------------------------------------- #
# stitching synthetic shards
# --------------------------------------------------------------------- #
def _write_shard(d, pid, spans=(), records=()):
    os.makedirs(d, exist_ok=True)
    evs = [{"name": n, "ph": "X", "ts": ts, "dur": 5.0, "pid": pid,
            "tid": 0, "args": args} for n, ts, args in spans]
    with open(os.path.join(d, "trace.json"), "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_stitch_merges_shards_folds_records_and_is_idempotent(tmp_path):
    run = str(tmp_path)
    _write_shard(run, 100,
                 spans=[("route", 10.0, {"trace_id": "t1"})],
                 records=[{"kind": "dispatch", "request": "r", "ts_us":
                           12.0, "reason": "failover", "re_emitted": 0,
                           "replica": "replica-0", "trace_id": "t1"}])
    _write_shard(os.path.join(run, "replica-0-i0"), 200,
                 spans=[("serve/prefill", 20.0, {"trace_ids": ["t1"]})],
                 records=[{"kind": "fault", "fault": "replica_crash",
                           "target": "replica-0", "phase": "injected",
                           "ts_us": 11.0}])
    trace = telemetry.stitch_trace(run)
    assert sorted(trace["stitched"]["pids"]) == [100, 200]
    names = [e["name"] for e in trace["traceEvents"]]
    # per-pid process_name metadata + spans + folded record instants
    assert names.count("process_name") == 2
    assert "dispatch/failover" in names and "fault/injected" in names
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert all({"name", "ph", "ts"} <= set(e) for e in meta)
    # the timeline of t1 is causally ordered: fault -> failover -> span
    tl = telemetry.request_timeline(trace, "t1")
    assert [e["name"] for e in tl] == ["route", "dispatch/failover",
                                      "serve/prefill"]
    # idempotent: a re-stitch must not duplicate metadata or instants
    again = telemetry.stitch_trace(run)
    assert len(again["traceEvents"]) == len(trace["traceEvents"])


def test_stitch_skips_records_without_ts_stamp(tmp_path):
    _write_shard(str(tmp_path), 1, records=[
        {"kind": "dispatch", "request": "r", "reason": "route",
         "re_emitted": 0, "replica": "x"}])   # pre-tracing record
    trace = telemetry.stitch_trace(str(tmp_path))
    assert all(e["ph"] == "M" for e in trace["traceEvents"])


# --------------------------------------------------------------------- #
# in-process propagation: Router / fleet / disagg
# --------------------------------------------------------------------- #
def test_fleet_failover_trace_propagates_and_check_passes(factory,
                                                          tmp_path):
    telemetry.configure(out_dir=str(tmp_path))
    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    router.step()
    fleet.inject("replica-0", "crash")
    done = router.run()
    tids = {rid: done[rid].trace_id for rid in rids}
    assert all(tids.values()) and len(set(tids.values())) == len(rids)
    recs = telemetry.get().step_records()
    dispatches = [r for r in recs if r.get("kind") == "dispatch"]
    serves = [r for r in recs if r.get("kind") == "serve"]
    # every dispatch AND serve record is trace-tagged with a known id
    assert dispatches and serves
    assert {r["trace_id"] for r in dispatches} <= set(tids.values())
    assert {r["trace_id"] for r in serves} <= set(tids.values())
    # the failover causal chain is in the records: the failed-over
    # trace has a prior dispatch onto the replica it fled
    fo = next(r for r in dispatches if r["reason"] == "failover")
    assert any(r["trace_id"] == fo["trace_id"]
               and r["replica"] == fo["from_replica"]
               for r in dispatches if r is not fo)
    telemetry.flush()
    assert tr.check_schema(str(tmp_path)) == []
    # the flushed trace resolves every completion's id to real spans
    with open(tmp_path / "trace.json") as f:
        trace = json.load(f)
    for rid in rids:
        assert telemetry.request_timeline(trace, tids[rid])


def test_disagg_handoff_carries_trace_and_gateB_passes(tmp_path):
    telemetry.configure(out_dir=str(tmp_path))
    srv = DisaggServer(tiny_engine_factory, prefill_replicas=1,
                       decode_replicas=1)
    rid = srv.submit([1, 2, 3], max_new_tokens=4, rid="r0")
    done = srv.run()
    tid = done[rid].trace_id
    assert tid
    handoff = next(r for r in telemetry.get().step_records()
                   if r.get("kind") == "handoff")
    assert handoff["trace_id"] == tid
    telemetry.flush()
    assert tr.check_schema(str(tmp_path)) == []
    with open(tmp_path / "trace.json") as f:
        trace = json.load(f)
    names = {e["name"] for e in
             telemetry.request_timeline(trace, tid)}
    assert "disagg/prefill" in names and "disagg/decode" in names


# --------------------------------------------------------------------- #
# the shared rolling window + aggregator
# --------------------------------------------------------------------- #
def test_rolling_window_empty_single_eviction_resize():
    w = RollingWindow(maxlen=3)
    assert w.percentile(99) is None and w.mean() is None and len(w) == 0
    w.push(5.0)
    assert w.percentile(50) == 5.0 and w.percentile(99) == 5.0
    for v in (1.0, 2.0, 3.0):
        w.push(v)           # 5.0 evicted: window holds [1, 2, 3]
    assert w.percentile(50) == 2.0 and len(w) == 3
    w.resize(2)             # keeps the most RECENT values
    assert list(w.values()) == [2.0, 3.0]
    with pytest.raises(ValueError):
        RollingWindow(maxlen=0)


def test_aggregator_slo_gauges_and_error_rate():
    agg = TelemetryAggregator(slo_ttft_p99_ms=10.0)
    out = agg.emit_slo_gauges()          # empty windows gauge 0.0
    assert out["slo/ttft_p99_ms"] == 0.0 and out["slo/error_rate"] == 0.0
    agg.observe_completion(ttft_s=0.02, e2e_s=0.1, finish_reason="eos")
    agg.observe_completion(ttft_s=0.04, e2e_s=0.2, finish_reason="shed")
    out = agg.emit_slo_gauges()
    assert out["slo/error_rate"] == 0.5
    assert out["slo/ttft_burn"] == pytest.approx(
        out["slo/ttft_p99_ms"] / 10.0)
    snap = {g["name"]: g["value"]
            for g in telemetry.get().registry.snapshot()
            if g["kind"] == "gauge"}
    assert snap["slo/ttft_p99_ms"] == out["slo/ttft_p99_ms"]


def test_aggregator_tails_worker_shards_incrementally(tmp_path):
    shard = tmp_path / "replica-0-i0"
    shard.mkdir()
    path = shard / "metrics.jsonl"
    rec = {"kind": "serve", "request": "a", "ttft_ms": 7.0,
           "inter_token_p99_ms": 2.0, "finish": "eos"}
    path.write_text(json.dumps(rec) + "\n")
    agg = TelemetryAggregator()
    assert agg.tail_shards(str(tmp_path)) == 1
    assert agg.tail_shards(str(tmp_path)) == 0    # offset remembered
    with open(path, "a") as f:
        f.write(json.dumps(dict(rec, request="b", finish="shed")) + "\n")
    assert agg.tail_shards(str(tmp_path)) == 1    # only the new record
    assert agg.requests == 2 and agg.errors == 1
    # a replacement incarnation REWRITES its shard: offset resets
    path.write_text(json.dumps(dict(rec, request="c")) + "\n")
    assert agg.tail_shards(str(tmp_path)) == 1


def test_router_and_autoscaler_read_identical_percentiles(factory):
    """The dedup pin: the hedge calibration and the TTFT trigger are
    views over ONE aggregator window — identical percentile reads on
    the identical completion stream, no private copies left."""
    fleet = ServingFleet(factory, replicas=2)
    router = Router(fleet)
    scaler = Autoscaler(router, config=AutoscaleConfig(ttft_window=64))
    rids = [router.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    router.run()
    assert not hasattr(router, "_latencies")     # private deque deleted
    assert not hasattr(scaler, "_ttfts")
    win = router.aggregator.window("ttft_ms")
    assert len(win) == len(rids)
    assert scaler.ttft_p99_ms() == win.percentile(99)
    # the hedge deadline reads the same aggregator's e2e window
    router.config.hedge_percentile, router.config.hedge_factor = 50, 2.0
    router.config.hedge_min_samples = 1
    e2e = router.aggregator.window("e2e_s")
    assert router._hedge_deadline() == pytest.approx(
        e2e.percentile(50) * 2.0)
    # and the SLO gauge agrees with both views
    snap = {g["name"]: g["value"]
            for g in telemetry.get().registry.snapshot()
            if g["kind"] == "gauge"}
    assert snap["slo/ttft_p99_ms"] == win.percentile(99)


# --------------------------------------------------------------------- #
# online drift monitor
# --------------------------------------------------------------------- #
def test_drift_monitor_edge_triggers_both_directions():
    mon = DriftMonitor({"step_time": 0.1}, every_n_steps=2,
                       threshold=0.25, window=4)
    for s in range(2):
        mon.observe_step(s, 0.1)          # ratio 1.0: inside the band
    assert mon.events == []
    for s in range(2, 4):
        mon.observe_step(s, 0.2)          # ratio -> 2.0: over
    assert [e["direction"] for e in mon.events] == ["over"]
    over = mon.events[-1]
    assert over["term"] == "step_time" and over["ratio"] > 1.25
    for s in range(4, 8):
        mon.observe_step(s, 0.2)          # still over: NO re-emission
    assert len(mon.events) == 1
    for s in range(8, 14):
        mon.observe_step(s, 0.02)         # ratio -> 0.2: under
    assert [e["direction"] for e in mon.events] == ["over", "under"]
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "drift"]
    assert len(recs) == 2
    snap = {g["name"]: g["value"]
            for g in telemetry.get().registry.snapshot()
            if g["kind"] == "gauge"}
    assert "drift/step_time_ratio" in snap


def test_runner_run_feeds_drift_monitor(monkeypatch):
    """The opt-in hook: DistributedRunner.run(drift_monitor=...) feeds
    every step's wall time — asserted through a stub runner so the
    hook's contract (observe_step per step) is pinned without a mesh."""
    from autodist_tpu import runner as runner_mod

    calls = []

    class _Mon:
        def observe_step(self, step, duration_s):
            calls.append((step, duration_s))

    class _Stub(runner_mod.DistributedRunner):
        def __init__(self):   # bypass mesh/compile machinery
            self._step_times = []
            self._run_steps_seen = 0
            self._run_seconds = 0.0
            self._run_examples = 0
            self._host_step = 1

        def step(self, batch):
            self._host_step += 1
            return {"loss": jnp.asarray(0.0)}

    stub = _Stub()
    stub.run(iter([{"x": jnp.zeros((2, 2))}] * 3), num_steps=3,
             drift_monitor=_Mon())
    assert len(calls) == 3
    assert all(d > 0 for _, d in calls)


# --------------------------------------------------------------------- #
# report: drift records, causal gates (mutation-tested both ways),
# back-compat on trace-id-less artifacts
# --------------------------------------------------------------------- #
def _run_dir(tmp_path, metrics, trace=None):
    d = tmp_path / "run"
    d.mkdir(exist_ok=True)
    with open(d / "metrics.jsonl", "w") as f:
        for r in metrics:
            f.write(json.dumps(r) + "\n")
    if trace is not None:
        with open(d / "trace.json", "w") as f:
            json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return str(d)


_FAULT = {"kind": "fault", "fault": "replica_crash",
          "target": "replica-0", "phase": "injected"}
_FAULT_R = dict(_FAULT, phase="recovered")
_FO = {"kind": "dispatch", "request": "r0", "replica": "replica-1",
       "reason": "failover", "re_emitted": 0,
       "from_replica": "replica-0", "trace_id": "tr-x"}
_ROUTE0 = {"kind": "dispatch", "request": "r0", "replica": "replica-0",
           "reason": "route", "re_emitted": 0, "trace_id": "tr-x"}


def test_gateA_failover_causal_chain_fires_and_stays_silent(tmp_path):
    # doctored: the trace never dispatched onto the replica it fled
    bad = tr.check_schema(_run_dir(tmp_path, [_FAULT, _FAULT_R, _FO]))
    assert any("causal chain" in p for p in bad)
    # honest: prior same-trace dispatch onto replica-0 exists
    ok = tr.check_schema(
        _run_dir(tmp_path, [_FAULT, _FAULT_R, _ROUTE0, _FO]))
    assert ok == []
    # back-compat: a trace-id-less failover passes on the old pairing
    legacy = {k: v for k, v in _FO.items() if k != "trace_id"}
    assert tr.check_schema(
        _run_dir(tmp_path, [_FAULT, _FAULT_R, legacy])) == []


_HANDOFF = {"kind": "handoff", "route": "ici", "blocks": 2,
            "bytes_moved": 10, "duration_ms": 1.0,
            "prefill_replica": "p0", "decode_replica": "d0",
            "trace_id": "tr-y"}


def _span(name, tid):
    return {"name": name, "ph": "X", "ts": 1.0, "dur": 2.0, "pid": 1,
            "tid": 0, "args": {"trace_ids": [tid]}}


def test_gateB_handoff_needs_both_spans(tmp_path):
    # doctored: tagged handoff, no tagged prefill/decode span at all
    bad = tr.check_schema(_run_dir(tmp_path, [_HANDOFF], trace=[]))
    assert any("causal chain" in p for p in bad)
    # doctored: prefill alone is NOT enough
    half = tr.check_schema(_run_dir(
        tmp_path, [_HANDOFF], trace=[_span("disagg/prefill", "tr-y")]))
    assert any("decode" in p for p in half)
    # honest: both halves tagged
    assert tr.check_schema(_run_dir(
        tmp_path, [_HANDOFF],
        trace=[_span("disagg/prefill", "tr-y"),
               _span("disagg/decode", "tr-y")])) == []
    # back-compat: an untagged handoff skips the gate
    legacy = {k: v for k, v in _HANDOFF.items() if k != "trace_id"}
    assert tr.check_schema(_run_dir(tmp_path, [legacy], trace=[])) == []


def test_drift_record_schema_gated_both_ways(tmp_path):
    rec = {"kind": "drift", "term": "step_time", "ratio": 1.6,
           "threshold": 0.25, "step": 4, "predicted": 0.1,
           "measured": 0.16, "direction": "over"}
    assert tr.check_schema(_run_dir(tmp_path, [rec])) == []
    inside = tr.check_schema(_run_dir(tmp_path, [dict(rec, ratio=1.1)]))
    assert any("never breached" in p for p in inside)
    missing = tr.check_schema(_run_dir(
        tmp_path, [{k: v for k, v in rec.items() if k != "ratio"}]))
    assert any("drift record missing" in p for p in missing)


def test_report_renders_trace_timeline_and_filter(tmp_path, capsys):
    d = _run_dir(tmp_path, [_ROUTE0],
                 trace=[_span("serve/prefill", "tr-x"),
                        _span("serve/decode", "tr-x")])
    assert tr.main([d]) == 0
    out = capsys.readouterr().out
    assert "## request traces" in out and "tr-x" in out
    assert tr.main([d, "--trace", "tr-x"]) == 0
    out = capsys.readouterr().out
    assert "### timeline — tr-x" in out and "serve/decode" in out
    assert tr.main([d, "--trace", "tr-nope"]) == 0
    assert "not found" in capsys.readouterr().out
