"""Vocab-parallel embedding/unembedding + the streaming fused
cross-entropy epilogue (``Pipeline(vocab_parallel=True)``).

Correctness is pinned the way the dp×pp×tp composition pinned TP
(``test_pipeline_tp.py``): goldens against the *sequential
single-device* reference — ``PipelineTrainable.loss`` runs the
replicated loss head (``models/losses.py``) on full parameters with
zero collectives — for vocab-parallel × tp ∈ {1, 2} across microbatch
counts, composed with ZeRO-1, bf16_ef, and ``comm_overlap``; plus the
edge cases the sharding introduces (V % tp ≠ 0 zero-pad, padded-row
exclusion from max/sum-exp/argmax) and a primitive-level fwd/bwd parity
test for :func:`vocab_parallel_cross_entropy` under ``shard_map``.

Tolerances mirror the TP goldens: sgd at 1e-5 rtol — vocab parallelism
only re-orders the softmax reduction sums.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import autodist_tpu._jax_compat  # noqa: F401  (jax.shard_map on 0.4.x)
from autodist_tpu import AutoDist
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig

SPEC_3D = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 2, "pipe": 2, "model": 2}}
SPEC_2D = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 4, "pipe": 2}}


def make_cfg(vocab=32):
    return TransformerConfig(vocab_size=vocab, hidden_size=16, num_layers=2,
                             num_heads=2, mlp_dim=32, max_len=8,
                             dtype=jnp.float32, dropout_rate=0.0,
                             attention_dropout_rate=0.0)


def make_lm(opt=None, cfg=None, seed=0):
    return make_pipeline_lm_trainable(cfg or make_cfg(),
                                      opt or optax.sgd(0.05),
                                      jax.random.PRNGKey(seed))


def lm_batches(n, vocab=32, seed=0):
    r = np.random.RandomState(seed)
    return [{"x": r.randint(0, vocab, (8, 8)).astype(np.int32),
             "y": r.randint(0, vocab, (8, 8)).astype(np.int32)}
            for _ in range(n)]


def sequential_train(trainable, batches):
    """Single-device reference: the trainable's own sequential loss."""
    params = trainable.params
    opt_state = trainable.optimizer.init(params)
    losses = []
    for b in batches:
        def loss_for(p):
            l, _, _ = trainable.loss(p, None, jax.tree.map(jnp.asarray, b),
                                     jax.random.PRNGKey(0))
            return l
        losses.append(float(loss_for(params)))
        g = jax.grad(loss_for)(params)
        upd, opt_state = trainable.optimizer.update(g, opt_state, params)
        params = optax.apply_updates(params, upd)
    return jax.device_get(params), losses


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


def run_and_compare(runner, trainable_fn, batches, rtol=1e-5, atol=1e-6):
    losses = [float(np.asarray(runner.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in batches]
    ref_params, ref_losses = sequential_train(trainable_fn(), batches)
    np.testing.assert_allclose(losses, ref_losses, rtol=rtol, atol=atol)
    assert_trees_close(runner.get_params(), ref_params, rtol=rtol,
                       atol=atol)


# --------------------------------------------------------------------------- #
# Primitive-level fwd/bwd parity
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("vocab", [10, 9])   # divisible and zero-padded
def test_cross_entropy_primitive_fwd_bwd_parity(vocab):
    """vocab_parallel_cross_entropy under a 2-shard shard_map ==
    the replicated models/losses.py math — value, prediction, and
    gradients wrt hidden states AND the (re-assembled) sharded table —
    including the V % tp != 0 zero-pad with padded rows excluded from
    max/sum-exp/argmax."""
    from jax.sharding import Mesh
    from autodist_tpu.kernel.common import pad_axis_to
    from autodist_tpu.models.losses import cross_entropy_from_logits
    from autodist_tpu.parallel.tensor import (vocab_parallel_cross_entropy,
                                              vocab_pad)

    tp, B, L, H = 2, 2, 4, 8
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(B, L, H), jnp.float32)
    emb = jnp.asarray(r.randn(vocab, H) * 0.5, jnp.float32)
    targets = jnp.asarray(r.randint(0, vocab, (B, L)), jnp.int32)

    # reference: replicated log-softmax on full logits
    def ref_loss(x, emb):
        logits = x @ emb.T
        return jnp.mean(cross_entropy_from_logits(logits, targets))

    ref_val = ref_loss(x, emb)
    ref_dx, ref_demb = jax.grad(ref_loss, argnums=(0, 1))(x, emb)
    ref_pred = np.asarray((x @ emb.T).argmax(-1))

    padded = pad_axis_to(emb, 0, vocab + vocab_pad(vocab, tp))
    mesh = Mesh(np.array(jax.devices()[:tp]), ("model",))

    def local(x, emb_shard):
        def loss(x, e):
            nll, pred = vocab_parallel_cross_entropy(
                x, e, targets, vocab_size=vocab, model_axis="model",
                seq_chunk=2)
            return jnp.mean(nll), pred
        (val, pred), (dx, de) = jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True)(x, emb_shard)
        return val, pred, dx, de

    val, pred, dx, de = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P("model", None)),
        out_specs=(P(), P(), P(), P("model", None)),
        check_vma=False)(x, padded)

    np.testing.assert_allclose(float(val), float(ref_val), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(pred), ref_pred)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(de)[:vocab],
                               np.asarray(ref_demb), rtol=1e-5, atol=1e-6)
    # zero-padded rows never receive gradient
    np.testing.assert_array_equal(np.asarray(de)[vocab:], 0.0)


def test_vocab_parallel_embedding_exact():
    """The masked shard lookup + psum equals the full-table lookup
    bitwise (one shard contributes the row, the rest zeros)."""
    from jax.sharding import Mesh
    from autodist_tpu.kernel.common import pad_axis_to
    from autodist_tpu.parallel.tensor import (vocab_parallel_embedding,
                                              vocab_pad)

    tp, vocab, H = 2, 7, 4
    r = np.random.RandomState(0)
    emb = jnp.asarray(r.randn(vocab, H), jnp.float32)
    tokens = jnp.asarray(r.randint(0, vocab, (3, 5)), jnp.int32)
    padded = pad_axis_to(emb, 0, vocab + vocab_pad(vocab, tp))
    mesh = Mesh(np.array(jax.devices()[:tp]), ("model",))
    out = jax.shard_map(
        lambda t, e: vocab_parallel_embedding(t, e, model_axis="model"),
        mesh=mesh, in_specs=(P(), P("model", None)), out_specs=P(),
        check_vma=False)(tokens, padded)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(emb[tokens]))


# --------------------------------------------------------------------------- #
# End-to-end goldens vs the sequential reference
# --------------------------------------------------------------------------- #
def test_vocab_parallel_tp2_matches_sequential_reference():
    """The headline golden: dp=2 x pp=2 x tp=2 with the shared embedding
    vocab-sharded reproduces the sequential single-device reference —
    losses AND parameters — with the tied table genuinely stored
    P('model', None) and its optimizer state sharded alongside."""
    runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                      tensor_parallel=2, vocab_parallel=True).build(make_lm())
    run_and_compare(runner, make_lm, lm_batches(3))
    emb = runner.state["params"]["shared"]["embedding"]
    # jit round trips may normalize the trailing None away
    assert emb.sharding.spec in (P("model", None), P("model"))
    assert runner.state["params"]["shared"]["pos_embed"].sharding.spec == P()


def test_vocab_parallel_tp1_is_recorded_noop():
    """vocab_parallel=True with tensor_parallel=1 (no model axis): the
    knob is recorded in the strategy but the lowering replicates —
    exact parity with the sequential reference."""
    ad = AutoDist(SPEC_2D, "Pipeline", num_microbatches=2,
                  vocab_parallel=True)
    strategy = ad.build_or_load_strategy(make_lm())
    assert strategy.graph_config.parallel["vocab_parallel"] is True
    runner = ad.build(make_lm(), strategy)
    run_and_compare(runner, make_lm, lm_batches(2))


def test_vocab_parallel_non_divisible_vocab_zero_pads():
    """V=33 over tp=2: storage zero-pads to 34 rows, padded logits are
    excluded from max/sum-exp, get_params returns the unpadded [33, H]
    table, and the run reproduces the sequential reference."""
    cfg = make_cfg(vocab=33)
    runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                      tensor_parallel=2, vocab_parallel=True).build(
                          make_lm(cfg=cfg))
    assert runner.state["params"]["shared"]["embedding"].shape == (34, 16)
    run_and_compare(runner, lambda: make_lm(cfg=cfg),
                    lm_batches(3, vocab=33))
    assert runner.get_params()["shared"]["embedding"].shape == (33, 16)


@pytest.mark.slow
@pytest.mark.parametrize("num_microbatches", [1, 4])
def test_vocab_parallel_microbatch_counts_match(num_microbatches):
    runner = AutoDist(SPEC_3D, "Pipeline",
                      num_microbatches=num_microbatches,
                      tensor_parallel=2, vocab_parallel=True).build(make_lm())
    run_and_compare(runner, make_lm, lm_batches(2))


def test_vocab_parallel_comm_overlap_matches():
    """The epilogue psums lower through the PR 2 rs+ag machinery: same
    math, different summation order — goldens hold at the sgd
    tolerance for both decompositions."""
    for mode in ("rsag", "matmul"):
        runner = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                          tensor_parallel=2, vocab_parallel=True,
                          comm_overlap=mode).build(make_lm())
        run_and_compare(runner, make_lm, lm_batches(2))
        runner.close()


def test_vocab_parallel_zero1_shards_embedding_state_and_matches():
    """ZeRO composes with the vocab-sharded table *properly* (the
    ROADMAP carry-over): instead of warn-and-degrade, the model-sharded
    embedding's optimizer state shards ADDITIONALLY over pipe x data —
    flat moments at 1/(tp·pipe·data), update space
    P(('model','pipe','data')) — while model-replicated shared vars keep
    their flat (pipe x data) moments, and numerics match the plain run."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True).build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True,
                  zero1=True).build(make_lm())
    for b in lm_batches(2):
        r0.step(b, rng=jax.random.PRNGKey(0))
        r1.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(r1.get_params(), r0.get_params())

    ra = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True,
                  zero1=True).build(make_lm(optax.adam(1e-2)))
    ra.step(lm_batches(1)[0], rng=jax.random.PRNGKey(0))
    mu = ra.state["opt_state"][0].mu
    emb = mu["shared"]["embedding"]
    assert emb.ndim == 1
    assert emb.sharding.spec == P(("model", "pipe", "data")), \
        emb.sharding.spec
    # the parameter itself keeps its model-axis storage (state-only
    # extra sharding; the stored table is still [V_pad/tp, H] per shard)
    assert ra.state["params"]["shared"]["embedding"].sharding.spec \
        in (P("model"), P("model", None))
    ln = mu["shared"]["ln_final_scale"]
    assert ln.ndim == 1 and ln.sharding.spec == P(("pipe", "data"))
    # nothing degraded silently: the plan records no fallback for the
    # table (tp-sharded stage vars do degrade, with reasons recorded)
    deg = ra.lowered.zero_degraded
    assert "shared/embedding" not in deg
    assert any(k.startswith("stages/") for k in deg)


def test_vocab_parallel_zero3_degrades_to_state_sharding_with_record():
    """zero_stage=3 on the model-sharded table: the parameter is already
    1/tp-sharded, so stage 3 degrades to the state-sharding form — and
    the lowered plan records the reason (no log-warning contract)."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True).build(make_lm())
    r3 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True,
                  zero_stage=3).build(make_lm())
    for b in lm_batches(2):
        r0.step(b, rng=jax.random.PRNGKey(0))
        r3.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(r3.get_params(), r0.get_params())
    assert "shared/embedding" in r3.lowered.zero_degraded
    # model-replicated shared vars DO store stage-3 sharded
    ln = r3.state["params"]["shared"]["ln_final_scale"]
    assert ln.ndim == 1 and ln.sharding.spec == P(("pipe", "data"))


@pytest.mark.slow
def test_vocab_parallel_bf16_ef_compressor_composes():
    """bf16_ef over the data axis composes with the vocab-sharded
    embedding (its grad psums over pipe at full precision first, EF
    residual rows sized from the model-local shard)."""
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True).build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True,
                  compressor="bf16_ef").build(make_lm())
    for b in lm_batches(2):
        r0.step(b, rng=jax.random.PRNGKey(0))
        r1.step(b, rng=jax.random.PRNGKey(0))
    assert_trees_close(r1.get_params(), r0.get_params(), rtol=5e-2,
                       atol=5e-3)
    # embedding 32x16 = 512 over model(2) shards -> 256-length local
    # residual rows, one per device
    assert r1.state["sync_state"]["shared/embedding"].shape == (8, 256)


# --------------------------------------------------------------------------- #
# Strategy IR, validation, cost model
# --------------------------------------------------------------------------- #
def test_vocab_strategy_ir_round_trip_and_validation():
    from autodist_tpu.strategy.ir import Strategy
    from autodist_tpu.strategy.parallel_builders import Pipeline
    from autodist_tpu.resource import ResourceSpec

    ad = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, vocab_parallel=True)
    strategy = ad.build_or_load_strategy(make_lm())
    assert strategy.graph_config.parallel["vocab_parallel"] is True
    clone = Strategy.from_json(strategy.to_json())
    by_name = {n.var_name: n for n in clone.node_configs}
    assert by_name["shared/embedding"].partitioner.spec == ["model", None]
    assert by_name["shared/pos_embed"].partitioner is None

    rs3 = ResourceSpec(SPEC_3D)
    # a trainable with no shared params cannot vocab-shard
    from autodist_tpu import PipelineTrainable
    stacked = {"wi": {"kernel": jnp.zeros((2, 8, 16))},
               "wo": {"kernel": jnp.zeros((2, 16, 8))}}
    mlp = PipelineTrainable(
        lambda p, x, model_axis=None: x, stacked,
        lambda o, b: (jnp.mean(o), {}), optax.sgd(0.1), num_stages=2)
    with pytest.raises(ValueError, match="shared"):
        Pipeline(num_microbatches=2, tensor_parallel=2,
                 vocab_parallel=True).build(mlp, rs3)

    # a loss head that is not vocab-parallel aware is rejected at build
    # time (so AutoStrategy's candidate loop skips, not crashes)
    lm = make_lm()
    lm.loss_head = lambda outputs, batch, shared: (jnp.mean(outputs), {})
    with pytest.raises(ValueError, match="model_axis"):
        Pipeline(num_microbatches=2, tensor_parallel=2,
                 vocab_parallel=True).build(lm, rs3)

    # ... and with comm_overlap set, the head must accept that too —
    # at build time, so AutoStrategy skips instead of failing at compile
    lm2 = make_lm()
    lm2.loss_head = lambda outputs, batch, shared, model_axis=None: (
        jnp.mean(outputs), {})
    with pytest.raises(ValueError, match="comm_overlap"):
        Pipeline(num_microbatches=2, tensor_parallel=2,
                 vocab_parallel=True, comm_overlap="rsag").build(lm2, rs3)


def test_cost_model_vocab_parallel_divides_memory_terms():
    """Acceptance: embedding optimizer state and peak logits memory
    reduced by 1/tp under vocab_parallel=True, and the candidate
    ranking sees it."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import Pipeline

    t0, t1 = make_lm(), make_lm()
    for t in (t0, t1):
        t.tokens_per_step = 4096
        t.act_bytes_per_token = 64.0
    rs = ResourceSpec(SPEC_3D)
    cm = CostModel(rs)
    s0 = Pipeline(num_microbatches=2, tensor_parallel=2).build(t0, rs)
    s1 = Pipeline(num_microbatches=2, tensor_parallel=2,
                  vocab_parallel=True).build(t1, rs)
    c0 = cm.strategy_cost(t0, s0)
    c1 = cm.strategy_cost(t1, s1)
    # peak logits exactly /tp ...
    assert c1.peak_logits_bytes == pytest.approx(c0.peak_logits_bytes / 2)
    assert c1.peak_logits_bytes > 0
    # ... and total per-device memory strictly shrinks (embedding
    # params + moments + logits all divided)
    assert c1.mem_bytes_per_device < c0.mem_bytes_per_device
    V, H = 32, 16
    emb_bytes = V * H * 4.0
    expected_drop = (emb_bytes * (2.0 + cm.opt_state_multiplier) / 2
                     + c0.peak_logits_bytes / 2)
    assert (c0.mem_bytes_per_device - c1.mem_bytes_per_device) \
        == pytest.approx(expected_drop)
    # the epilogue's psums are priced: more collectives, more bytes
    assert c1.num_collectives > c0.num_collectives


def test_auto_strategy_zoo_ranks_vocab_parallel_candidate():
    """The AutoStrategy zoo scores the vocab-parallel candidate on a 3D
    mesh, and its memory column reflects the 1/tp shrink vs the
    blocking tp=2 candidate."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.auto_strategy import AutoStrategy

    lm = make_lm()
    lm.tokens_per_step = 4096
    lm.act_bytes_per_token = 64.0
    auto = AutoStrategy()
    auto.build(lm, ResourceSpec(SPEC_3D))
    # candidate names are positional (#k suffixes), so identify the
    # vocab-parallel candidate by its unique memory signature: the
    # pipeline candidate whose peak-logits term halved.
    logits_terms = sorted({c.peak_logits_bytes for _, c in auto.report
                           if c.peak_logits_bytes > 0})
    assert len(logits_terms) >= 2, "no vocab-parallel candidate scored"
    assert logits_terms[0] == pytest.approx(logits_terms[-1] / 2)
