"""Measure compressor allreduce cost ratios on the current backend.

The analytic cost model prices compressors by wire-byte counts
(``simulator/cost_model.py COMPRESSOR_FACTOR``), which ignores compute:
int8_ring pays p-1 *sequential* ppermute hops with per-hop requantization
and PowerSGD pays a per-step Gram-Schmidt.  This driver measures each
compressor's end-to-end allreduce wall-clock against the uncompressed
baseline on the live devices and writes ``calibration.json`` at the repo
root — loaded automatically by the cost model (``load_calibration``) so
AutoStrategy ranks with measured ratios instead of guesses.

On a single chip the collective itself is a no-op, so the measured ratio
captures the *compute* overhead (quantize/dequantize passes, power
iteration) — exactly the part the byte count misses; on a multi-device
mesh it also captures the wire.  The JSON records the topology so the
provenance is auditable.

Usage: ``python tools/calibrate_compressors.py [--size 26214400]``
"""
import argparse
import json
import os
import sys
import time

import jax

# The axon TPU plugin pins the backend at interpreter start; honoring the
# env through jax.config (which wins over the plugin) keeps
# JAX_PLATFORMS=cpu smoke runs off the tunnel.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from autodist_tpu import const
from autodist_tpu.kernel.compressor import Compressor


def time_compressor(name: str, mesh, x, steps: int = 10) -> float:
    comp = Compressor.create(name)
    state0 = None
    if comp.stateful:
        state0 = jnp.asarray(np.asarray(comp.init_state_flat(x.size),
                                        np.float32))

    def local(x, state):
        out, new_state = comp.allreduce(x, state, const.DATA_AXIS)
        return out, (new_state if comp.stateful else jnp.zeros((1,)))

    fn = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P() if comp.stateful else P()),
        out_specs=(P(), P()), check_vma=False))
    dummy = state0 if comp.stateful else jnp.zeros((1,))
    out, st = fn(x, dummy)          # compile
    float(np.asarray(out[0]))       # fence
    t0 = time.perf_counter()
    for _ in range(steps):
        out, st = fn(x, st if comp.stateful else dummy)
    float(np.asarray(out[0]))
    return (time.perf_counter() - t0) / steps


def time_quantize(precision: str, x, steps: int = 10) -> float:
    """Wall-clock of one quantize -> dequantize roundtrip (no
    collective): exactly the compute term the per-boundary precision
    policy's cost model charges against its byte savings
    (``simulator/cost_model.py QUANT_PROFILE``)."""
    from autodist_tpu.kernel import quantize as qz

    if precision == "bf16":
        def roundtrip(v):
            return v.astype(jnp.bfloat16).astype(jnp.float32)
    else:
        def roundtrip(v):
            q, scale = qz.quantize_int8(v)
            return qz.dequantize_int8(q, scale)

    fn = jax.jit(roundtrip)
    out = fn(x)                      # compile
    float(np.asarray(out[0]))        # fence
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(out)
    float(np.asarray(out[0]))
    return (time.perf_counter() - t0) / steps


def measure_quant(size: int, steps: int) -> dict:
    """The ``"quant"`` calibration section: measured quantize/dequantize
    seconds per element, per precision, timed at two boundary shapes (a
    TP-activation-sized payload and the full grad-bucket payload) with
    the larger shape setting the per-element constant — fixed overheads
    amortize there, which is the regime the cost model prices."""
    shapes = sorted({max(size // 64, 1), size})
    section: dict = {}
    shape_ms: dict = {}
    for prec in ("bf16", "int8"):
        per_elem = None
        for n in shapes:
            x = jnp.asarray(np.random.RandomState(1).randn(n)
                            .astype(np.float32))
            dt = time_quantize(prec, x, steps)
            shape_ms[f"{prec}_{n}"] = round(dt * 1e3, 4)
            per_elem = dt / n
        section[f"{prec}_s_per_elem"] = float(f"{per_elem:.4g}")
    return section, shape_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=26_214_400,
                    help="flat fp32 buffer elements (default ~100MB, "
                         "BERT-bucket scale)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "calibration.json"))
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    devs = np.array(jax.devices())
    mesh = Mesh(devs, (const.DATA_AXIS,))
    x = jnp.asarray(np.random.RandomState(0).randn(args.size)
                    .astype(np.float32))

    # q/dq compute per boundary shape FIRST (seconds of work, and the
    # term the per-boundary precision policy's pricing needs even if a
    # later compressor compile dies mid-run).
    quant, quant_shape_ms = measure_quant(args.size, args.steps)
    for k, v in quant.items():
        print(f"quant {k:18s} {v:.3e} s/elem", flush=True)

    names = ["none", "bf16", "bf16_ef", "int8_ef", "int8_ring",
             "powersgd:4"]
    times = {}

    def write_out():
        # Incremental, atomic: each compressor's compile can take
        # minutes on a degraded tunnel and the measurement queue runs
        # this under a timeout — factors measured so far must survive a
        # mid-run kill.
        base = times["none"]
        factors = {n.partition(":")[0]: round(t / base, 4)
                   for n, t in times.items() if n != "none"}
        record = {
            "compressor_factor": factors,
            # q/dq compute per element (the precision-policy pricing
            # term, simulator/cost_model.py QUANT_PROFILE) — loaded by
            # load_calibration like the "link" constants.
            "quant": quant,
            "meta": {
                "backend": jax.default_backend(),
                "device_kind": devs.flat[0].device_kind,
                "num_devices": int(devs.size),
                "buffer_elements": args.size,
                "baseline_ms": round(base * 1e3, 3),
                "quant_shape_ms": quant_shape_ms,
                "note": "wall-clock ratio vs uncompressed allreduce; on "
                        "one device this is compute overhead only (no "
                        "wire)",
            },
        }
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, args.out)
        return factors

    for name in names:
        try:
            times[name] = time_compressor(name, mesh, x, args.steps)
            print(f"{name:12s} {times[name]*1e3:8.3f} ms", flush=True)
            if "none" in times and len(times) > 1:
                factors = write_out()
        except Exception as e:  # a compressor that cannot run gets no entry
            print(f"{name:12s} FAILED: {e}", flush=True)
    if "none" not in times:
        raise SystemExit("baseline (none) failed; no calibration written")
    if len(times) == 1:
        raise SystemExit("only the baseline ran; no calibration written")
    print(f"wrote {args.out}: {factors}")


if __name__ == "__main__":
    main()
