"""Chaos harness: run a LocalCluster training job under a fault plan.

The executable proof behind every supervised-recovery path: a tiny
pipeline-LM trains on the 8-device simulated CPU mesh while a
``LocalCluster`` of real worker *processes* heartbeats through the
coordination service, and one fault from
:mod:`autodist_tpu.runtime.faults` is injected mid-run.  The run must
end in a supervised recovery (restart, degrade, or shrink-to-survivors
resume) or a clean coded teardown — never a hang, never a bare stack
trace — with a schema-valid ``kind="fault"`` record per injection and
the post-recovery loss trajectory matching the fault-free golden::

    # one fault kind
    JAX_PLATFORMS=cpu python tools/chaos_run.py --fault worker_crash

    # the full matrix: golden + every fault kind, each in its own
    # watchdogged subprocess (a hung scenario FAILS, loudly)
    JAX_PLATFORMS=cpu python tools/chaos_run.py --matrix

    # CI budget guard (remaining scenarios listed, never silently
    # dropped — the lint_strategy --max-programs pattern)
    JAX_PLATFORMS=cpu python tools/chaos_run.py --matrix --max-scenarios 3

    # the serving plane: replica_crash / replica_hang / replica_slow
    # against a 2-replica ServingFleet behind a Router — every request
    # must complete exactly once, token-for-token equal to the
    # single-replica fault-free golden, with zero leaked KV blocks
    JAX_PLATFORMS=cpu python tools/chaos_run.py --matrix --plane serving

    # the same serving faults against REAL replica processes
    # (ProcessFleet over the coordination service): the plan ships to
    # the workers and replica-0 self-injects its own death — a crash
    # is a dead process, a hang a SIGSTOP — while the golden stays
    # in-process as the token-parity anchor
    JAX_PLATFORMS=cpu python tools/chaos_run.py --matrix \
        --plane serving --processes

Per-kind expected outcome:

=================  =====================================================
worker_crash       supervisor restarts the worker (``phase=recovered``)
worker_hang        heartbeat monitor declares it dead (``detected``),
                   SIGKILL, restart (``recovered``)
slow_host          worker stalls under the heartbeat timeout; no kill,
                   run completes (``recovered`` from the worker itself)
coord_drop         server bounced; clients reconnect-and-retry
                   (``recovered``; ``coord/reconnects`` counters move)
ckpt_write_fail    Saver retries, then coded degrade on the last good
                   checkpoint (``degraded``); training never stops
preempt_signal     SIGTERM → blocking elastic checkpoint → re-search on
                   survivors → reshard → resume (``recovered``, the
                   PR 11 flow, loss within the reshard tolerance)
=================  =====================================================
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

if __name__ == "__main__":  # simulated mesh before the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flag = "--xla_force_host_platform_device_count=8"
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

# The one registry: a fault kind added to runtime/faults.py joins the
# matrix (and this CLI's choices) automatically.
from autodist_tpu.runtime.faults import FAULT_KINDS as FAULTS  # noqa: E402
from autodist_tpu.runtime.faults import \
    SERVING_FAULT_KINDS as SERVING_FAULTS  # noqa: E402

SCENARIOS = ("none",) + FAULTS
# The serving plane (--plane serving): the replica fault kinds against
# a two-replica ServingFleet behind a Router, fixed request mix,
# token-for-token parity vs the single-replica fault-free golden.
SERVING_SCENARIOS = ("none",) + SERVING_FAULTS

# Loss tolerance vs the fault-free golden: faults that never touch the
# chief's math must reproduce it exactly; preempt_signal reshards onto
# half the mesh (PR 11), so its trajectory is close, not bit-equal.
RTOL_EXACT, RTOL_RESHARD = 1e-6, 2e-3

_HB_INTERVAL_S = 0.2
_HB_TIMEOUT_S = 1.2


def make_plan(kind: str, steps: int):
    """The one-fault plan for ``kind`` (an empty plan for the golden).
    Worker faults trigger on wall-time (the workers don't step the
    model); chief faults trigger on the training step."""
    from autodist_tpu.runtime.faults import FaultPlan, FaultSpec

    mid = max(steps // 2, 1)
    spec = {
        "none": None,
        "worker_crash": FaultSpec("worker_crash", target="worker-1",
                                  at_s=1.0),
        "worker_hang": FaultSpec("worker_hang", target="worker-1",
                                 at_s=1.0),
        "slow_host": FaultSpec("slow_host", target="worker-1", at_s=1.0,
                               duration_s=0.6),
        "coord_drop": FaultSpec("coord_drop", target="coord",
                                at_step=mid, duration_s=0.4),
        "ckpt_write_fail": FaultSpec("ckpt_write_fail", target="chief",
                                     at_step=2, times=3),
        "preempt_signal": FaultSpec("preempt_signal", target="chief",
                                    at_step=mid),
    }[kind]
    return FaultPlan(faults=[spec] if spec else [], seed=1234)


# --------------------------------------------------------------------------- #
# Worker process (launched by the chief through the LocalCluster — the
# same re-launch-the-user-script model as a real fleet; detected via
# the AUTODIST_TPU_WORKER env marker)
# --------------------------------------------------------------------------- #
def run_worker() -> int:
    from autodist_tpu import telemetry
    from autodist_tpu.runtime import cluster, coordination, faults

    name = f"worker-{os.environ.get('AUTODIST_TPU_PROCESS_ID', '0')}"
    incarnation = int(os.environ.get("AUTODIST_TPU_WORKER_INCARNATION",
                                     "0"))
    iters = int(os.environ.get("CHAOS_WORKER_ITERS", "50"))
    base = os.environ.get("CHAOS_WORKER_TELEMETRY", "")
    if base:
        telemetry.configure(out_dir=os.path.join(
            base, f"{name}-i{incarnation}"))
    client = coordination.service_client()
    if client is not None:
        cluster.heartbeat(client, name, interval_s=_HB_INTERVAL_S)
    injector = None
    plan = faults.load_fault_plan()
    if plan is not None and incarnation == 0:
        # A restarted incarnation must not re-inject its own death.
        injector = faults.FaultInjector(plan, self_target=name)
    for i in range(iters):
        if injector is not None:
            injector.maybe_fire(i)
        time.sleep(0.1)
    if injector is not None:
        injector.drain_pending(iters)   # a late at_s trigger still fires
    if base:
        telemetry.flush()
    return 0


# --------------------------------------------------------------------------- #
# One scenario (chief): train under the plan, assert the outcome
# --------------------------------------------------------------------------- #
def _build_runner(num_devices: int = 8):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.strategy.parallel_builders import Pipeline

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    trainable = make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                           jax.random.PRNGKey(0))
    ad = AutoDist({"topology": {"num_devices": num_devices},
                   "mesh": {"data": num_devices // 2, "pipe": 2}},
                  Pipeline(num_microbatches=2))
    runner = ad.build(trainable)

    def make_batch(step):
        r = np.random.RandomState(1000 + step)
        x = r.randint(0, 64, (8, 8)).astype(np.int32)
        y = np.concatenate([x[:, 1:], x[:, :1]], axis=1)
        return {"x": x, "y": y}

    return trainable, runner, make_batch


def run_scenario(kind: str, steps: int, tel_dir: str,
                 out_path: str) -> int:
    import numpy as np

    from autodist_tpu import telemetry
    from autodist_tpu.analysis import lint_supervision
    from autodist_tpu.checkpoint.saver import Saver
    from autodist_tpu.elastic import ElasticController
    from autodist_tpu.runtime.cluster import LocalCluster, SupervisionConfig
    from autodist_tpu.runtime.faults import FaultInjector
    from autodist_tpu.runtime.retry import RetryPolicy

    telemetry.configure(out_dir=tel_dir)
    plan = make_plan(kind, steps)
    trainable, runner, make_batch = _build_runner()
    ckpt_dir = tempfile.mkdtemp(prefix=f"chaos_ckpt_{kind}_")
    saver = Saver(ckpt_dir,
                  retry=RetryPolicy(max_attempts=2, base_delay_s=0.05,
                                    cap_delay_s=0.1, seed=plan.seed),
                  degrade_on_failure=True)
    controller = ElasticController(trainable, saver, global_batch=8)
    controller.install(runner)
    supervision = SupervisionConfig(
        max_restarts=1,
        restart_backoff=RetryPolicy(max_attempts=2, base_delay_s=0.2,
                                    cap_delay_s=0.2, seed=plan.seed),
        heartbeat_interval_s=_HB_INTERVAL_S,
        heartbeat_timeout_s=_HB_TIMEOUT_S,
        escalate=True, saver=saver)
    sup_report = lint_supervision(supervision)
    if not sup_report.ok:
        print(sup_report.render("supervision lint"), file=sys.stderr)
        return 2
    cluster = LocalCluster(2, supervision=supervision)
    extra_env = plan.ship({
        "CHAOS_WORKER_ITERS": str(max(int(steps * 2.5), 45)),
        "CHAOS_WORKER_TELEMETRY": tel_dir,
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        # workers need no simulated mesh and must not inherit ours
        "XLA_FLAGS": "", "JAX_PLATFORMS": "cpu",
    })
    problems: list[str] = []
    try:
        cluster.launch_clients(None, extra_env=extra_env)
        cluster.start_heartbeat_monitor()
        from autodist_tpu.runtime.coordination import service_client

        injector = FaultInjector(plan, self_target="chief", saver=saver,
                                 coord_bounce=cluster.bounce_coord_service)
        losses = []
        for step in range(steps):
            injector.maybe_fire(step)
            if controller.preempted:
                runner = controller.resume({"num_devices": 4})
            # The chief reports its own progress through the control
            # plane every step — so the step right after a coord_drop
            # bounce hits the dead socket DETERMINISTICALLY and pins the
            # reconnect-and-retry path (worker/monitor threads also hit
            # it, but only when their poll lands inside the window).
            client = service_client()
            if client is not None:
                client.counter_add("chief/steps", 1)
            metrics = runner.step(make_batch(step))
            losses.append(float(np.asarray(metrics["loss"])))
            if step % 5 == 3:   # a cadence that never collides with the
                #                 mid-run preemption checkpoint's step
                saver.save(runner)
            time.sleep(0.15)   # stretch wall-time so worker faults and
            #                    their detection overlap the run
        # Workers run longer than the loop; join must be clean —
        # a crash beyond supervision would raise here.
        cluster.join(timeout=120)
    finally:
        cluster.terminate()
    saver.wait()
    telemetry.flush()
    _merge_worker_metrics(tel_dir)
    problems += _check_outcome(kind, tel_dir)
    record = {"kind": "chaos_scenario", "fault": kind, "steps": steps,
              "losses": losses, "problems": problems,
              "ok": not problems}
    with open(out_path, "w") as f:
        json.dump(record, f)
    print(f"chaos[{kind}]: {'OK' if not problems else problems}")
    return 0 if not problems else 1


def _merge_worker_metrics(tel_dir: str):
    """Fold every worker incarnation's fault records into the chief's
    metrics.jsonl — ONE log for the schema gate, like a real fleet's
    log aggregation."""
    main = os.path.join(tel_dir, "metrics.jsonl")
    lines = []
    for entry in sorted(os.listdir(tel_dir)):
        sub = os.path.join(tel_dir, entry, "metrics.jsonl")
        if not (entry.startswith(("worker-", "replica-"))
                and os.path.exists(sub)):
            continue
        with open(sub) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "fault":
                    lines.append(json.dumps(rec))
    if lines:
        with open(main, "a") as f:
            f.write("\n".join(lines) + "\n")


def _check_outcome(kind: str, tel_dir: str) -> list[str]:
    """Scenario acceptance: schema-clean artifacts (including the
    injected↔outcome pairing the report gates) plus the per-kind
    recovery shape."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import check_schema, load_jsonl

    problems = list(check_schema(tel_dir))
    records = load_jsonl(os.path.join(tel_dir, "metrics.jsonl"))
    faults = [r for r in records if r.get("kind") == "fault"]
    counters = {r["name"]: r["value"] for r in records
                if r.get("kind") == "counter"}

    def has(phase, fault=None, **kv):
        return any(r.get("phase") == phase
                   and (fault is None or r.get("fault") == fault)
                   and all(r.get(k) == v for k, v in kv.items())
                   for r in faults)

    if kind == "none":
        if faults:
            problems.append(f"golden run emitted fault records: {faults}")
        return problems
    if not has("injected", kind):
        problems.append(f"no injected record for {kind}")
    if kind in ("worker_crash", "worker_hang"):
        if not has("recovered", kind, action="restart"):
            problems.append(f"{kind}: no supervised restart recorded")
        if kind == "worker_hang" and not has("detected", kind):
            problems.append("worker_hang: heartbeat monitor never "
                            "declared the worker dead")
    elif kind == "slow_host":
        if not has("recovered", kind):
            problems.append("slow_host: no recovery record")
        if counters.get("runtime/worker_restarts"):
            problems.append("slow_host: a slow-but-alive worker was "
                            "restarted (heartbeat timeout too tight)")
    elif kind == "coord_drop":
        if not has("recovered", kind):
            problems.append("coord_drop: no server-restart record")
        if not counters.get("coord/reconnect_successes"):
            problems.append("coord_drop: no client ever reconnected "
                            "(chief-side); the retry path never ran")
    elif kind == "ckpt_write_fail":
        if not has("degraded", kind):
            problems.append("ckpt_write_fail: Saver never degraded "
                            "onto the last good checkpoint")
        if not counters.get("ckpt/save_failures"):
            problems.append("ckpt_write_fail: ckpt/save_failures "
                            "counter never moved")
    elif kind == "preempt_signal":
        if not has("recovered", kind, action="shrink_resume"):
            problems.append("preempt_signal: no shrink-resume recovery "
                            "record")
    return problems


# --------------------------------------------------------------------------- #
# The serving plane: replica faults against a 2-replica fleet
# --------------------------------------------------------------------------- #
# The fixed request mix every serving scenario serves (prompt,
# max_new_tokens): short ragged prompts whose decode spans the
# injection point, so a mid-stream failure always has in-flight
# requests to re-home.
SERVE_MIX = ([1, 2, 3], 8), ([4, 5], 8), ([6], 8), ([7, 8, 9], 8), \
    ([3, 1], 8), ([2, 9, 4], 8)


def _build_fleet(kind: str, *, processes: bool = False, tel_dir=None,
                 fault_plan=None):
    """The scenario fleet: 1 fault-free replica for the golden, 2 for
    every fault — hedging armed only for the straggler scenario so the
    crash/hang recoveries are unambiguously the failover path's.

    Both planes serve through :func:`tiny_engine_factory` (the
    deterministic PRNGKey(0) engine), so the in-process golden IS the
    parity anchor for the cross-process scenarios: any process that
    builds the engine from the same kwargs emits the same tokens.

    ``processes=True`` swaps in a :class:`ProcessFleet` — real replica
    processes over the coordination service, the fault plan shipped for
    worker self-injection — with the heartbeat window widened to
    cross-process scale (a replacement spawn takes seconds of worker
    boot, not microseconds of object construction)."""
    from autodist_tpu.serving import FleetConfig, ServingFleet
    from autodist_tpu.serving.remote import ProcessFleet, tiny_engine_factory

    if processes and kind != "none":
        fleet_config = FleetConfig(
            replicas=2,
            hedge_timeout_s=0.5 if kind == "replica_slow" else None,
            hedge_percentile=None,
            max_replacements=1,
            heartbeat_interval_s=0.1, heartbeat_timeout_s=2.0,
            heartbeat_startup_grace_s=30.0)
        return ProcessFleet(
            {"factory": "autodist_tpu.serving.remote:tiny_engine_factory"},
            config=fleet_config, telemetry_dir=tel_dir,
            fault_plan=fault_plan)
    fleet_config = FleetConfig(
        replicas=1 if kind == "none" else 2,
        hedge_timeout_s=0.2 if kind == "replica_slow" else None,
        hedge_percentile=None,
        max_replacements=1,
        heartbeat_interval_s=0.05, heartbeat_timeout_s=0.5,
        heartbeat_startup_grace_s=0.5)
    return ServingFleet(tiny_engine_factory, config=fleet_config)


def _await_worker_fault_records(kind: str, tel_dir: str,
                                timeout_s: float = 15.0) -> None:
    """Block until the self-injecting worker's fault records hit its
    telemetry file: the straggler flushes its injected+resumed pair
    only after its stall ends, which may be after the chief's requests
    all hedged away and completed — merging before that flush would
    fail the injected↔outcome pairing for a recovery that DID run."""
    want = {"injected"} if kind in ("replica_crash", "replica_hang") \
        else {"injected", "recovered"}
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        seen = set()
        for entry in sorted(os.listdir(tel_dir)):
            sub = os.path.join(tel_dir, entry, "metrics.jsonl")
            if not (entry.startswith("replica-") and os.path.exists(sub)):
                continue
            with open(sub) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("kind") == "fault" \
                            and rec.get("fault") == kind:
                        seen.add(rec.get("phase"))
        if want <= seen:
            return
        time.sleep(0.2)


def run_serving_scenario(kind: str, tel_dir: str, out_path: str,
                         processes: bool = False) -> int:
    """One serving scenario: the fixed mix through a fleet under one
    injected replica fault; every request must complete exactly once
    with zero leaked KV blocks and a schema-clean dispatch/fault
    trail.  Token parity vs the golden is the matrix driver's join.

    ``processes=True`` runs the fault against REAL replica processes
    (:class:`ProcessFleet`): the plan ships to the workers and
    replica-0 self-injects its own death/stall ``at_s`` seconds after
    its first submitted request — the chief holds no injector at all,
    so the failure truly arrives from outside the scheduler loop.  The
    golden stays in-process: parity is by construction of the shared
    ``tiny_engine_factory``, and a fault-free remote run would only
    re-prove the RPC mirror, which the remote-serving unit tests own."""
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.faults import (FaultInjector, FaultPlan,
                                             FaultSpec)
    from autodist_tpu.serving import Router

    telemetry.configure(out_dir=tel_dir)
    processes = processes and kind != "none"
    spec = None
    if kind != "none":
        spec = FaultSpec(kind, target="replica-0", at_s=0.5,
                         duration_s=1.5) if processes else \
            FaultSpec(kind, target="replica-0", at_step=2,
                      duration_s=1.0)
    plan = FaultPlan(faults=[spec] if spec else [], seed=1234)
    fleet = _build_fleet(kind, processes=processes, tel_dir=tel_dir,
                         fault_plan=plan)
    router = Router(fleet)
    # In-process: the chief owns the injection (it holds the fleet).
    # Cross-process: the WORKER owns it (self-injection from the
    # shipped plan) — a chief-side injector here would double-fire.
    injector = None if processes \
        else FaultInjector(plan, self_target="chief", fleet=fleet)
    rids = [router.submit(p, max_new_tokens=m) for p, m in SERVE_MIX[:4]]
    rnd = 0
    while router._open or rnd < 4:
        if injector is not None:
            injector.maybe_fire(rnd)
        if rnd == 3:   # late arrivals keep the queue live mid-fault
            rids += [router.submit(p, max_new_tokens=m)
                     for p, m in SERVE_MIX[4:]]
        router.step()
        if processes:
            time.sleep(0.01)   # remote rounds poll RPC; don't spin hot
        rnd += 1
    # A short mix can finish inside a transient fault's window (every
    # request hedged off the straggler): keep the scheduler alive until
    # the fault resolves — the injector.drain_pending analog; ending
    # early would green-light a resume record that never fired.
    if not processes:
        while any(r._fault is not None for r in fleet.live):
            router.step()
            time.sleep(0.02)
    telemetry.flush()
    if processes:
        _await_worker_fault_records(kind, tel_dir)
        _merge_worker_metrics(tel_dir)
    # One stitched chrome-trace per scenario (chief shard + any worker
    # shards): the injected fault must be VISIBLE in it — asserted in
    # the outcome check below.
    telemetry.stitch_trace(tel_dir)
    problems = _check_serving_outcome(kind, tel_dir, fleet, router, rids)
    if processes:
        fleet.close()
    record = {"kind": "chaos_scenario", "plane": "serving", "fault": kind,
              "tokens": {rid: router.completions[rid].tokens
                         for rid in rids if rid in router.completions},
              "finish": {rid: router.completions[rid].finish_reason
                         for rid in rids if rid in router.completions},
              "problems": problems, "ok": not problems}
    with open(out_path, "w") as f:
        json.dump(record, f)
    print(f"chaos[serving/{kind}]: {'OK' if not problems else problems}")
    return 0 if not problems else 1


def _check_serving_outcome(kind, tel_dir, fleet, router, rids) -> list:
    """Exactly-once + zero-leak + per-kind recovery shape (the
    schema gate covers the dispatch/fault record contracts)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from telemetry_report import check_schema, load_jsonl

    problems = list(check_schema(tel_dir))
    # exactly once: every submitted request has exactly one completion,
    # and a *decode* terminal (nothing shed/expired/stranded)
    missing = [r for r in rids if r not in router.completions]
    if missing:
        problems.append(f"requests never completed: {missing}")
    for rid in rids:
        comp = router.completions.get(rid)
        if comp is not None and comp.finish_reason not in (
                "eos", "max_tokens", "max_len"):
            problems.append(f"{rid} ended {comp.finish_reason!r}, not a "
                            "decode terminal")
    # zero leaked KV blocks on every live replica
    for name, (free, used, total) in fleet.block_accounting().items():
        if used != 0 or free != total:
            problems.append(f"{name} leaked KV blocks: free={free} "
                            f"used={used} total={total}")
    records = load_jsonl(os.path.join(tel_dir, "metrics.jsonl"))
    faults = [r for r in records if r.get("kind") == "fault"]
    dispatches = [r for r in records if r.get("kind") == "dispatch"]

    def has(phase, **kv):
        return any(r.get("phase") == phase
                   and all(r.get(k) == v for k, v in kv.items())
                   for r in faults)

    # Every injected fault must be VISIBLE in the stitched trace: the
    # scenario stitches the chief + worker span shards into one
    # chrome-trace, and an injection whose ``fault/injected`` instant
    # never landed on any process's track is a trace that cannot
    # explain its own failover.
    try:
        with open(os.path.join(tel_dir, "trace.json")) as f:
            trace_events = json.load(f).get("traceEvents", [])
    except (OSError, ValueError):
        trace_events = []
    fault_instants = {((e.get("args") or {}).get("fault"),
                      (e.get("args") or {}).get("target"))
                      for e in trace_events
                      if str(e.get("name", "")).startswith(
                          "fault/injected")}
    for rec in faults:
        if rec.get("phase") != "injected":
            continue
        if (rec.get("fault"), rec.get("target")) not in fault_instants:
            problems.append(
                f"injected fault {rec.get('fault')}@{rec.get('target')} "
                "has no fault/injected instant in the stitched "
                "trace.json — the injection is invisible to the trace")

    reasons = {r.get("reason") for r in dispatches}
    if kind == "none":
        if faults:
            problems.append(f"golden run emitted fault records: {faults}")
        if reasons - {"route"}:
            problems.append(f"golden run dispatched non-route reasons: "
                            f"{sorted(reasons - {'route'})}")
        return problems
    if not has("injected", fault=kind):
        problems.append(f"no injected record for {kind}")
    if kind in ("replica_crash", "replica_hang"):
        if not has("detected", fault=kind, target="replica-0"):
            problems.append(f"{kind}: the fleet never detected the "
                            "dead replica")
        if "failover" not in reasons:
            problems.append(f"{kind}: no failover dispatch — the "
                            "re-home path never ran")
        if not has("recovered", fault=kind, action="replace"):
            problems.append(f"{kind}: the dead replica was never "
                            "replaced")
    elif kind == "replica_slow":
        if not has("recovered", fault=kind, action="resumed"):
            problems.append("replica_slow: the straggler never "
                            "recorded its resume")
        if "hedge" not in reasons:
            problems.append("replica_slow: no hedged dispatch — the "
                            "straggler path never ran")
        if has("detected", fault="replica_hang") \
                or has("detected", fault="replica_slow"):
            problems.append("replica_slow: a slow-but-beating replica "
                            "was declared dead (hedging territory, "
                            "not the health check's)")
    return problems


def run_serving_matrix(scenario_timeout: float,
                       max_scenarios: int | None, out_dir: str,
                       processes: bool = False) -> int:
    """Golden + every serving fault kind, each subprocessed and
    watchdogged; token-for-token parity joined against the golden.
    With ``processes=True`` the fault scenarios run against real
    replica processes (the golden stays in-process — the parity
    anchor), so the join proves the RPC plane re-homes mid-stream work
    token-for-token across an actual process death."""
    results = {}
    golden_tokens = None
    todo = list(SERVING_SCENARIOS)
    skipped = []
    if max_scenarios is not None and len(todo) > max_scenarios:
        todo, skipped = todo[:max_scenarios], todo[max_scenarios:]
    for kind in todo:
        tel_dir = os.path.join(out_dir, kind)
        out_json = os.path.join(out_dir, f"{kind}.json")
        os.makedirs(tel_dir, exist_ok=True)
        argv = [sys.executable, os.path.abspath(__file__),
                "--plane", "serving", "--run-one", kind,
                "--telemetry-dir", tel_dir, "--out", out_json]
        if processes:
            argv.append("--processes")
        t0 = time.monotonic()
        try:
            proc = subprocess.run(argv, timeout=scenario_timeout,
                                  env=dict(os.environ))
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            results[kind] = {"ok": False,
                             "problems": [f"scenario hung beyond "
                                          f"{scenario_timeout}s"]}
            print(f"chaos[serving/{kind}]: HUNG after "
                  f"{scenario_timeout}s")
            continue
        rec = {"ok": False, "problems": [f"scenario exited rc={rc} "
                                         "with no result record"]}
        if os.path.exists(out_json):
            with open(out_json) as f:
                rec = json.load(f)
        rec["rc"] = rc
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        if kind == "none":
            golden_tokens = rec.get("tokens")
        elif golden_tokens and rec.get("tokens"):
            # Token-for-token: a failure mode may re-route, hedge, or
            # re-prefill a request, but the client stream must be the
            # golden's, byte for byte.
            for rid, want in golden_tokens.items():
                got = rec["tokens"].get(rid)
                if got != want:
                    rec["ok"] = False
                    rec.setdefault("problems", []).append(
                        f"{rid}: tokens {got} != golden {want}")
        results[kind] = rec
    print("\n== serving chaos matrix ==")
    failed = []
    for kind, rec in results.items():
        status = "OK" if rec.get("ok") and rec.get("rc", 1) == 0 \
            else f"FAIL ({rec.get('problems')})"
        print(f"  {kind:16s} {status}  [{rec.get('wall_s', '?')}s]")
        if "OK" not in status:
            failed.append(kind)
    for kind in skipped:
        print(f"  {kind:16s} SKIPPED (--max-scenarios budget)")
    with open(os.path.join(out_dir, "matrix.json"), "w") as f:
        json.dump(results, f, indent=1)
    return 1 if failed else 0


# --------------------------------------------------------------------------- #
# The matrix driver
# --------------------------------------------------------------------------- #
def run_matrix(steps: int, scenario_timeout: float,
               max_scenarios: int | None, out_dir: str) -> int:
    results = {}
    golden_losses = None
    todo = list(SCENARIOS)
    skipped = []
    if max_scenarios is not None and len(todo) > max_scenarios:
        # Loud budget guard: the golden always runs; dropped scenarios
        # are listed, never silently truncated.
        todo, skipped = todo[:max_scenarios], todo[max_scenarios:]
    for kind in todo:
        tel_dir = os.path.join(out_dir, kind)
        out_json = os.path.join(out_dir, f"{kind}.json")
        os.makedirs(tel_dir, exist_ok=True)
        argv = [sys.executable, os.path.abspath(__file__),
                "--run-one", kind, "--steps", str(steps),
                "--telemetry-dir", tel_dir, "--out", out_json]
        t0 = time.monotonic()
        try:
            proc = subprocess.run(argv, timeout=scenario_timeout,
                                  env=dict(os.environ))
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            # A hang IS a failure — the whole point of the harness.
            results[kind] = {"ok": False,
                            "problems": [f"scenario hung beyond "
                                         f"{scenario_timeout}s"]}
            print(f"chaos[{kind}]: HUNG after {scenario_timeout}s")
            continue
        rec = {"ok": False, "problems": [f"scenario exited rc={rc} "
                                         "with no result record"]}
        if os.path.exists(out_json):
            with open(out_json) as f:
                rec = json.load(f)
        rec["rc"] = rc
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        if kind == "none":
            golden_losses = rec.get("losses")
        elif golden_losses and rec.get("losses"):
            rtol = RTOL_RESHARD if kind == "preempt_signal" else RTOL_EXACT
            a, b = golden_losses[-1], rec["losses"][-1]
            if abs(a - b) > rtol * max(abs(a), abs(b), 1e-9):
                rec["ok"] = False
                rec.setdefault("problems", []).append(
                    f"final loss {b} drifted from golden {a} beyond "
                    f"rtol={rtol}")
        results[kind] = rec
    print("\n== chaos matrix ==")
    failed = []
    for kind, rec in results.items():
        status = "OK" if rec.get("ok") and rec.get("rc", 1) == 0 \
            else f"FAIL ({rec.get('problems')})"
        print(f"  {kind:16s} {status}  [{rec.get('wall_s', '?')}s]")
        if "OK" not in status:
            failed.append(kind)
    for kind in skipped:
        print(f"  {kind:16s} SKIPPED (--max-scenarios budget)")
    with open(os.path.join(out_dir, "matrix.json"), "w") as f:
        json.dump(results, f, indent=1)
    return 1 if failed else 0


def main(argv=None) -> int:
    from autodist_tpu import const

    if const.ENV.AUTODIST_TPU_WORKER.val:
        return run_worker()   # we ARE a launched worker
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plane", choices=("train", "serving"),
                    default="train",
                    help="which chaos plane to sweep: the LocalCluster "
                         "training run (default) or the 2-replica "
                         "serving fleet (replica_* fault kinds)")
    ap.add_argument("--fault", choices=SCENARIOS + SERVING_FAULTS,
                    help="run one scenario inline")
    ap.add_argument("--run-one", choices=SCENARIOS + SERVING_FAULTS,
                    help="(internal) one scenario in this process")
    ap.add_argument("--matrix", action="store_true",
                    help="golden + every fault kind, each subprocessed "
                         "and watchdogged")
    ap.add_argument("--processes", action="store_true",
                    help="serving plane only: run the fault scenarios "
                         "against REAL replica processes (ProcessFleet "
                         "+ worker self-injection); the golden stays "
                         "in-process as the parity anchor")
    ap.add_argument("--steps", type=int, default=14)
    ap.add_argument("--scenario-timeout", type=float, default=600.0)
    ap.add_argument("--max-scenarios", type=int, default=None,
                    help="CI budget guard: run only the first N "
                         "scenarios, loudly listing the skipped rest")
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.run_one or args.fault:
        kind = args.run_one or args.fault
        plane = "serving" if kind in SERVING_FAULTS else args.plane
        valid = SERVING_SCENARIOS if plane == "serving" else SCENARIOS
        if kind not in valid:
            ap.error(f"fault {kind!r} is not a --plane {plane} "
                     f"scenario (choose from {list(valid)})")
        if args.processes and plane != "serving":
            ap.error("--processes is a serving-plane switch (the "
                     "training plane's LocalCluster is already "
                     "process-backed)")
        tel_dir = args.telemetry_dir or tempfile.mkdtemp(
            prefix=f"chaos_{kind}_")
        out = args.out or os.path.join(tel_dir, "result.json")
        if plane == "serving":
            return run_serving_scenario(kind, tel_dir, out,
                                        processes=args.processes)
        return run_scenario(kind, args.steps, tel_dir, out)
    if args.matrix:
        if args.processes and args.plane != "serving":
            ap.error("--processes is a serving-plane switch (the "
                     "training plane's LocalCluster is already "
                     "process-backed)")
        out_dir = args.telemetry_dir or tempfile.mkdtemp(prefix="chaos_")
        print(f"chaos matrix artifacts: {out_dir}")
        if args.plane == "serving":
            return run_serving_matrix(args.scenario_timeout,
                                      args.max_scenarios, out_dir,
                                      processes=args.processes)
        return run_matrix(args.steps, args.scenario_timeout,
                          args.max_scenarios, out_dir)
    ap.error("pick one of --fault/--matrix")


if __name__ == "__main__":
    sys.exit(main())
