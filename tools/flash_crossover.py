"""Measure the einsum-vs-Pallas-flash attention crossover on real hardware.

Round-3 verdict Weak #2: at seq 512 plain einsum beats this repo's flash
kernel and the long-context win was only a projection.  This driver
measures fwd+bwd wall-clock of both attention implementations across
sequence lengths and block sizes, printing one JSON line per point —
the curve that goes into BASELINE.md and justifies (or bounds) when the
bench self-tuner should pick the kernel.

Usage: ``python tools/flash_crossover.py [--seqs 512,1024,2048,4096]``

``--decode`` switches to the serving-side crossover: single-query-per-
slot shapes (one token attending over a KV cache of each ``--seqs``
length) at ``--fill`` slot-length fractions, comparing the composed
einsum cache attention (``serving/kv_cache.cached_attention``) against
the Pallas flash-decode kernel.  Each point prints one provenance-
stamped record in the bench schema, and ``--write-calibration`` merges
the measured crossover into calibration.json's ``"kernel"`` section
(``flash_decode_crossover_len`` / ``flash_decode_speedup``) — the
constants ``CostModel.decode_cost`` elects the kernel by.
"""
import argparse
import json
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):  # allow CPU smoke off the tunnel
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from autodist_tpu.ops.flash_attention import flash_attention


def attention_flops(b, l, h, d):
    """fwd matmul FLOPs: scores (2*b*h*l*l*d) + values (same); x3 fwd+bwd
    (bwd recompute excluded — both impls pay their own)."""
    return 3.0 * 2.0 * 2.0 * b * h * l * l * d


def einsum_attention(q, k, v, causal):
    depth = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(depth)
    s = s.astype(jnp.float32)
    if causal:
        L = q.shape[1]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", p, v)


def fence(out):
    """Host round-trip on one scalar that depends on the computation —
    honest timing on proxied backends (see bench.py)."""
    return float(np.asarray(jax.tree.leaves(out)[0]).ravel()[0])


def timed(fn, args, steps):
    fence(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="512,1024,2048,4096")
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--tokens", type=int, default=8192,
                    help="per-step token budget: batch = tokens // seq")
    ap.add_argument("--causal", action="store_true")
    ap.add_argument("--blocks", default="128,256,512",
                    help="flash block sizes to try (best reported)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--write", default="",
                    help="merge results into this flash_tuning.json "
                         "(per-length best blocks + crossover_len; the "
                         "kernel's default blocks and the flash_wins() "
                         "helper read it — commit it at the repo root)")
    ap.add_argument("--decode", action="store_true",
                    help="measure the serving-side crossover instead: "
                         "single-query flash-decode vs the composed "
                         "einsum cache attention over --seqs cache "
                         "lengths")
    ap.add_argument("--prefill", action="store_true",
                    help="measure the chunked-prefill crossover: the "
                         "paged flash-prefill kernel vs the composed "
                         "gather path over --chunks chunk sizes at "
                         "each --seqs cache length; "
                         "--write-calibration merges "
                         "flash_prefill_crossover_chunk / "
                         "flash_prefill_speedup into the 'kernel' "
                         "section")
    ap.add_argument("--chunks", default="64,128,256,512",
                    help="--prefill: prefill chunk sizes to sweep")
    ap.add_argument("--slots", type=int, default=8,
                    help="--decode: batch slots per step")
    ap.add_argument("--fill", default="1.0,0.5",
                    help="--decode: slot-length fractions of the cache "
                         "length (the occupancy distribution decode "
                         "actually sees)")
    ap.add_argument("--write-calibration", default="",
                    metavar="PATH",
                    help="--decode: merge the measured crossover into "
                         "this calibration.json's 'kernel' section "
                         "(flash_decode_crossover_len / "
                         "flash_decode_speedup)")
    args = ap.parse_args()
    if args.decode:
        return _main_decode(args)
    if args.prefill:
        return _main_prefill(args)

    H, D = args.heads, args.head_dim
    causal = bool(args.causal)
    records = []
    wrote = False
    for L in [int(s) for s in args.seqs.split(",")]:
        B = max(args.tokens // L, 1)
        r = np.random.RandomState(0)
        q, k, v = (jnp.asarray(r.randn(B, L, H, D), jnp.bfloat16)
                   for _ in range(3))

        def make_grad(attn):
            def loss(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)
            return jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))

        t_einsum = timed(make_grad(
            lambda q, k, v: einsum_attention(q, k, v, causal)),
            (q, k, v), args.steps)

        best = None
        for blk in [int(b) for b in args.blocks.split(",")]:
            if blk > L:
                continue
            try:
                t = timed(make_grad(
                    lambda q, k, v, blk=blk: flash_attention(
                        q, k, v, causal=causal, block_q=blk, block_k=blk)),
                    (q, k, v), args.steps)
                if best is None or t < best[0]:
                    best = (t, blk)
            except Exception as e:
                print(f"# flash L={L} block={blk} failed: {e}",
                      file=sys.stderr)
        t_flash, blk = best if best else (float("nan"), 0)
        rec = {
            "seq": L, "batch": B, "heads": H, "head_dim": D,
            "causal": causal,
            "einsum_ms": round(t_einsum * 1e3, 3),
            "flash_ms": round(t_flash * 1e3, 3),
            "flash_block": blk,
            "flash_speedup": round(t_einsum / t_flash, 3)
            if t_flash == t_flash else None,
            "attn_tflops_einsum": round(
                attention_flops(B, L, H, D) / t_einsum / 1e12, 2),
        }
        records.append(rec)
        print(json.dumps(rec), flush=True)
        if args.write:
            # Merge-write after EVERY length, not once at the end: on a
            # degraded tunnel each point costs minutes of compiles and
            # the queue's timeout can fire mid-run — measured points
            # must survive the kill.
            wrote = _merge_write(records, args.write, causal) or wrote
    wins = [r for r in records if (r["flash_speedup"] or 0) > 1.0]
    print(json.dumps({
        "summary": "flash wins from seq "
                   f"{min((r['seq'] for r in wins), default=None)}"
                   if wins else "einsum wins at every measured length",
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }))
    if args.write and not wrote:
        print("# no successful flash timing; tuning table unchanged",
              file=sys.stderr)


def _main_decode(args):
    """The ``--decode`` mode: one record per (cache length, fill)
    point, bench-schema-shaped and provenance-stamped; the summary line
    derives the crossover, and ``--write-calibration`` commits it."""
    from autodist_tpu.serving.kv_cache import cached_attention
    from autodist_tpu.kernel.pallas.flash_decode import \
        flash_decode_attention
    from autodist_tpu.telemetry.records import provenance

    H, D, B = args.heads, args.head_dim, args.slots
    fills = [float(f) for f in args.fill.split(",")]
    records = []
    for T in [int(s) for s in args.seqs.split(",")]:
        r = np.random.RandomState(0)
        q = jnp.asarray(r.randn(B, 1, H, D), jnp.bfloat16)
        k = jnp.asarray(r.randn(B, H, T, D), jnp.bfloat16)
        v = jnp.asarray(r.randn(B, H, T, D), jnp.bfloat16)
        for fill in fills:
            lengths = jnp.full((B,), max(int(T * fill) - 1, 0),
                               jnp.int32)
            t_einsum = timed(jax.jit(
                lambda q, k, v, l: cached_attention(
                    q, k, v, l, dtype=jnp.bfloat16)),
                (q, k, v, lengths), args.steps)
            try:
                t_flash = timed(jax.jit(
                    lambda q, k, v, l: flash_decode_attention(
                        q, k, v, l, dtype=jnp.bfloat16)),
                    (q, k, v, lengths), args.steps)
            except Exception as e:
                print(f"# flash decode T={T} fill={fill} failed: {e}",
                      file=sys.stderr)
                continue
            rec = {
                "metric": "flash_decode_crossover",
                "kv_len": T, "fill": fill, "slots": B, "heads": H,
                "head_dim": D,
                "einsum_ms": round(t_einsum * 1e3, 4),
                "flash_ms": round(t_flash * 1e3, 4),
                "value": round(t_einsum / t_flash, 4),
                "unit": "ratio", "scored": True,
                "provenance": provenance(),
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)
    wins = sorted({r["kv_len"] for r in records if r["value"] > 1.0})
    crossover = wins[0] if wins else None
    speedups = [r["value"] for r in records
                if crossover is not None and r["kv_len"] >= crossover]
    summary = {
        "summary": (f"flash decode wins from kv_len {crossover}"
                    if crossover is not None
                    else "einsum wins at every measured cache length"),
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(summary))
    if args.write_calibration and records:
        if jax.default_backend() == "cpu":
            # Interpreter timings say nothing about the TPU kernel and
            # would mislead every chip's planning (load_calibration has
            # no per-section provenance to filter them back out).
            print("# refusing to write CPU-measured kernel constants "
                  f"into {args.write_calibration}", file=sys.stderr)
            return
        table = {}
        if os.path.exists(args.write_calibration):
            try:
                with open(args.write_calibration) as f:
                    table = json.load(f)
            except (OSError, ValueError):
                table = {}
        kern = dict(table.get("kernel", {}))
        if crossover is not None:
            kern["flash_decode_crossover_len"] = crossover
            kern["flash_decode_speedup"] = round(
                sum(speedups) / len(speedups), 3)
        else:
            # Flash never won: push the crossover past every measured
            # length so the cost model stops electing it in this range.
            kern["flash_decode_crossover_len"] = 2 * max(
                r["kv_len"] for r in records)
        table["kernel"] = kern
        meta = dict(table.get("meta", {}))
        meta["kernel_source"] = (
            f"tools/flash_crossover.py --decode on "
            f"{jax.devices()[0].device_kind} "
            f"({provenance().get('git_sha', '')[:12]})")
        table["meta"] = meta
        tmp = args.write_calibration + ".tmp"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1)
        os.replace(tmp, args.write_calibration)
        print(f"# wrote kernel section to {args.write_calibration}",
              file=sys.stderr)


def _main_prefill(args):
    """The ``--prefill`` mode: one record per (cache length, chunk
    size) point — the paged flash-prefill kernel against its composed
    gather golden on identical block tables — and the summary derives
    the chunk-size crossover.  ``--write-calibration`` merges
    ``flash_prefill_crossover_chunk`` / ``flash_prefill_speedup`` into
    the ``"kernel"`` section ``CostModel`` loads, closing the loop:
    ``default_serving_candidates(ladder=True)`` seeds its chunked
    candidate at exactly this measured chunk."""
    from autodist_tpu.kernel.pallas.flash_prefill import \
        flash_prefill_attention_paged
    from autodist_tpu.serving.kv_cache import paged_chunk_attention
    from autodist_tpu.telemetry.records import provenance

    H, D, B = args.heads, args.head_dim, args.slots
    records = []
    chunks = [int(c) for c in args.chunks.split(",")]
    for T in [int(s) for s in args.seqs.split(",")]:
        bl = 16
        max_blocks = -(-T // bl)
        r = np.random.RandomState(0)
        k_pool = jnp.asarray(
            r.randn(B * max_blocks, H, bl, D), jnp.bfloat16)
        v_pool = jnp.asarray(
            r.randn(B * max_blocks, H, bl, D), jnp.bfloat16)
        table = jnp.asarray(
            r.permutation(B * max_blocks).reshape(B, max_blocks),
            jnp.int32)
        for C in chunks:
            if C > T:
                continue
            q = jnp.asarray(r.randn(B, C, H, D), jnp.bfloat16)
            # every slot's chunk starts mid-prompt: rows attend through
            # earlier blocks via the table, the shape the chunked
            # prefill loop dispatches
            starts = jnp.full((B,), T - C, jnp.int32)
            t_gather = timed(jax.jit(
                lambda q, s, t: paged_chunk_attention(
                    q, k_pool, v_pool, s, t, block_len=bl,
                    dtype=jnp.bfloat16)),
                (q, starts, table), args.steps)
            try:
                t_flash = timed(jax.jit(
                    lambda q, s, t: flash_prefill_attention_paged(
                        q, k_pool, v_pool, s, t, block_len=bl,
                        dtype=jnp.bfloat16)),
                    (q, starts, table), args.steps)
            except Exception as e:
                print(f"# flash prefill T={T} chunk={C} failed: {e}",
                      file=sys.stderr)
                continue
            rec = {
                "metric": "flash_prefill_crossover",
                "kv_len": T, "chunk": C, "slots": B, "heads": H,
                "head_dim": D, "block_len": bl,
                "gather_ms": round(t_gather * 1e3, 4),
                "flash_ms": round(t_flash * 1e3, 4),
                "value": round(t_gather / t_flash, 4),
                "unit": "ratio", "scored": True,
                "provenance": provenance(),
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)
    wins = sorted({r["chunk"] for r in records if r["value"] > 1.0})
    crossover = wins[0] if wins else None
    speedups = [r["value"] for r in records
                if crossover is not None and r["chunk"] >= crossover]
    print(json.dumps({
        "summary": (f"flash prefill wins from chunk {crossover}"
                    if crossover is not None
                    else "the composed gather wins at every measured "
                         "chunk size"),
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }))
    if args.write_calibration and records:
        if jax.default_backend() == "cpu":
            print("# refusing to write CPU-measured kernel constants "
                  f"into {args.write_calibration}", file=sys.stderr)
            return
        table = {}
        if os.path.exists(args.write_calibration):
            try:
                with open(args.write_calibration) as f:
                    table = json.load(f)
            except (OSError, ValueError):
                table = {}
        kern = dict(table.get("kernel", {}))
        if crossover is not None:
            kern["flash_prefill_crossover_chunk"] = crossover
            kern["flash_prefill_speedup"] = round(
                sum(speedups) / len(speedups), 3)
        else:
            kern["flash_prefill_crossover_chunk"] = 2 * max(
                r["chunk"] for r in records)
        table["kernel"] = kern
        meta = dict(table.get("meta", {}))
        meta["kernel_prefill_source"] = (
            f"tools/flash_crossover.py --prefill on "
            f"{jax.devices()[0].device_kind} "
            f"({provenance().get('git_sha', '')[:12]})")
        table["meta"] = meta
        tmp = args.write_calibration + ".tmp"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1)
        os.replace(tmp, args.write_calibration)
        print(f"# wrote kernel section to {args.write_calibration}",
              file=sys.stderr)


def _merge_write(records, path, causal) -> bool:
    """Merge measured points into the tuning table the kernel reads, PER
    LENGTH: previously measured lengths (and the other causal-ness
    branch) are preserved; lengths where flash failed to run write
    nothing — a measurement failure must stay distinguishable from
    "flash measured and lost" (flash_wins derives the verdict from the
    per-length speedup records at read time)."""
    ok = [r for r in records
          if r["flash_block"] and r["flash_speedup"] is not None]
    if not ok:
        return False
    table = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                loaded = json.load(f)
            table = loaded if isinstance(loaded, dict) else {}
        except (OSError, ValueError):
            table = {}
    if table and table.get("backend") != jax.default_backend():
        # Cross-backend merge would mislabel stale entries under this
        # run's provenance stamp (or discard this run's via the old
        # stamp) — measurements from different backends don't compose;
        # start a fresh table.  Unstamped legacy tables have unknown
        # provenance: same treatment.
        print(f"# discarding {path} measured on "
              f"{table.get('backend')!r} (this run: "
              f"{jax.default_backend()!r})", file=sys.stderr)
        table = {}
    key = "causal" if causal else "noncausal"
    branch = table.get(key)
    branch = dict(branch) if isinstance(branch, dict) else {}
    blocks = branch.get("blocks")
    blocks = dict(blocks) if isinstance(blocks, dict) else {}
    speedup = branch.get("speedup")
    speedup = dict(speedup) if isinstance(speedup, dict) else {}
    for r in ok:
        blocks[str(r["seq"])] = r["flash_block"]
        speedup[str(r["seq"])] = r["flash_speedup"]
    branch["blocks"] = blocks
    branch["speedup"] = speedup
    measured_wins = sorted(int(k) for k, v in speedup.items() if v > 1.0)
    branch["crossover_len"] = measured_wins[0] if measured_wins else None
    table[key] = branch
    table["device_kind"] = jax.devices()[0].device_kind
    # Provenance: load_tuning refuses to auto-load CPU-measured tables
    # (interpret-mode timings would mislead TPU defaults).
    table["backend"] = jax.default_backend()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1)
    os.replace(tmp, path)   # a mid-write kill must not corrupt the table
    print(f"# wrote {path}", file=sys.stderr)
    return True


if __name__ == "__main__":
    main()
