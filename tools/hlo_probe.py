"""HLO-structural proof of the framework's performance claims — on CPU.

Round-5 VERDICT demanded silicon-free falsifiability: every "we emit
fewer/better collectives" claim must be checkable without the flaky TPU
tunnel.  This probe lowers real train-step programs with
``jax.jit(...).lower(...).compile()`` on simulated CPU meshes and
asserts collective *counts and kinds* in the optimized HLO text:

* ``probe_steps_per_loop`` — ``run_steps``'s k-step program is ONE HLO
  module whose scan is a ``while`` loop with the *same* collective
  counts as the single-step program: k optimizer steps fuse into one
  dispatch instead of unrolling (or worse, k dispatches).
* ``probe_single_replica`` — the single-replica allreduce bypass
  (kernel/lowering.py): a 1-device program contains zero ``all-reduce``
  ops.
* ``probe_pipeline_tp`` — the dp×pp×tp composition: at
  ``tensor_parallel=2`` the pipeline step carries the per-stage
  ``model``-axis activation all-reduces (Megatron's one-per-block,
  forward and backward) *on top of* the tp=1 program's collectives, and
  both carry the ``collective-permute`` stage ring.
* ``probe_collective_matmul`` — the latency-hiding decomposition
  (``Pipeline(comm_overlap=...)``): the converted program carries ZERO
  monolithic model-axis all-reduce (its all-reduce count equals the
  tp=1 program's — nothing re-fused) while emitting the decomposed
  forms instead: ≥ tp−1 extra ``collective-permute`` (the chunked
  collective-matmul ring) plus ``reduce-scatter``/``all-gather`` pairs.
* ``probe_vocab_parallel`` — vocab parallelism
  (``Pipeline(vocab_parallel=True)``): the vocab-sharded tp=2 program
  contains no full-vocab-sized buffer and no vocab-axis all-gather
  anywhere (distinctive-dimension shape scan), vs. the replicated
  baseline which carries the ``[V, H]`` table and ``[.., V]`` logits —
  a silent re-replication of the loss head fails CI on CPU.
* ``probe_quantized`` — the per-collective precision policy
  (``Pipeline(collective_precision=...)``): an int8-policy tp=2 program
  carries the narrowed element type on every policied collective
  operand (fp16 levels wire on psums, TRUE s8 on gathers, with the
  convert pairs), un-policied fp32 boundaries stay untouched, the
  quantized decomposed rs+ag pair stays un-re-fused, and the int8
  ZeRO-3 gathers narrow per layer.
* ``probe_decode`` — the serving engine's fused decode step
  (``autodist_tpu/serving/``): the vocab-parallel tp=2 program carries
  zero full-vocab buffers, no ``[T, T]`` attention-score square, KV
  writes via in-place ``dynamic-update-slice`` on donated (aliased)
  cache buffers with no full-cache copy, and one fused ``while`` loop
  per K-token window.
* ``probe_zero3`` — ZeRO-2/3 on the tp×dp mesh
  (``Pipeline(zero_stage=...)``): the stage-3 program's *step boundary*
  (the ENTRY signature: donated-in state + returned state) carries ZERO
  buffers of the distinctive full-parameter extent — parameters live
  only as flat shards between steps — while emitting >= per-layer
  all-gathers (one per (virtual stage, leaf); a collective-combiner
  pass merging them into one bulk materialization, or a re-gather of
  full storage, fails here); the stage-2 program syncs gradients by
  reduce-scatter where the stage-0 baseline has none.

Run as a script for a JSON report::

    JAX_PLATFORMS=cpu python tools/hlo_probe.py            # all probes
    JAX_PLATFORMS=cpu python tools/hlo_probe.py --json out.json
    JAX_PLATFORMS=cpu python tools/hlo_probe.py --probe single_replica
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

if __name__ == "__main__":  # simulated mesh before the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

# HLO spells ops `%name = type all-reduce(...)`; async TPU lowerings
# split into -start/-done pairs — count the -start as the op.
_COLLECTIVE_RE = re.compile(
    r"=\s*(?:\([^)]*\)|\S+)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")

# Every typed array shape in HLO text: `f32[8,8,93]{2,1,0}` etc.
_SHAPE_RE = re.compile(
    r"\b(?:pred|s4|u4|s8|u8|s16|u16|s32|u32|s64|u64|"
    r"f8\w*|bf16|f16|f32|f64|c64|c128)\[([0-9,]*)\]")

# Same scan keeping the element type — the quantized-collectives probe
# asserts the *dtype* on the wire, not just the op kind.
_TYPED_SHAPE_RE = re.compile(
    r"\b(pred|s4|u4|s8|u8|s16|u16|s32|u32|s64|u64|"
    r"f8\w*|bf16|f16|f32|f64|c64|c128)\[([0-9,]*)\]")

# Result-type prefix + collective kind: `%x = f16[8]{0} all-reduce(...)`
# or the tuple/async forms `= (s8[4], s8[4]) all-gather-start(...)`.
_COLLECTIVE_TYPED_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")

# Wire dtypes a narrowed boundary may carry: bf16 casts, f16 int8-level
# sums, true-s8 gathers (and any future fp8 wire).
_NARROW_DTYPES = ("bf16", "f16", "s8", "u8", "f8")


def collective_counts(hlo_text: str) -> dict[str, int]:
    """Count collective ops by kind in optimized HLO text."""
    counts = collections.Counter(_COLLECTIVE_RE.findall(hlo_text))
    return {k: counts.get(k, 0)
            for k in ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all")}


def collective_wire(hlo_text: str) -> list[tuple[str, str, int]]:
    """Every collective op's ``(kind, element_type, result_elements)``
    from optimized HLO text — the wire-dtype analog of
    :func:`collective_counts` (async ``-start`` forms count once; for
    tuple results the widest element drives the entry)."""
    out = []
    for m in _COLLECTIVE_TYPED_RE.finditer(hlo_text):
        prefix, kind = m.group(1), m.group(2)
        best = None
        for dt, dims in _TYPED_SHAPE_RE.findall(prefix):
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            if best is None or elems > best[1]:
                best = (dt, elems)
        if best is None:
            best = ("", 0)
        out.append((kind, best[0], best[1]))
    return out


def narrowed_collective_counts(hlo_text: str) -> dict[str, int]:
    """Collectives whose wire element type is narrower than fp32, by
    kind — zero everywhere for an fp32-policy program; the policied
    boundaries for a narrowed one."""
    counts: dict[str, int] = {
        k: 0 for k in ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all")}
    for kind, dtype, _ in collective_wire(hlo_text):
        if any(dtype.startswith(n) for n in _NARROW_DTYPES):
            counts[kind] += 1
    return counts


def nonscalar_all_reduces(hlo_text: str) -> int:
    """All-reduce ops with a result of more than one element: the
    shared-scale pmaxes a quantized boundary adds are scalars, so this
    count isolates the payload-carrying reductions — a monolithic
    model-axis all-reduce surviving (or re-fusing after) a decomposition
    shows up here."""
    return sum(1 for kind, _, elems in collective_wire(hlo_text)
               if kind == "all-reduce" and elems > 1)


_CONVERT_RE = re.compile(r"=\s*(\w+)\[[0-9,]*\][^ ]*\s*convert\(")


def convert_counts(hlo_text: str) -> dict[str, int]:
    """Count ``convert`` ops by result element type — the
    convert-before/convert-after halves of a narrowed boundary."""
    return dict(collections.Counter(_CONVERT_RE.findall(hlo_text)))


def buffers_with_dim(hlo_text: str, dim: int) -> int:
    """Count array shapes carrying ``dim`` in optimized HLO text — the
    memory-shape analog of :func:`collective_counts`: with a dim chosen
    to be distinctive (a vocab size no other tensor dimension equals),
    zero hits proves the program never materializes a buffer of that
    extent on any device."""
    hits = 0
    for m in _SHAPE_RE.finditer(hlo_text):
        dims = [int(d) for d in m.group(1).split(",") if d]
        if dim in dims:
            hits += 1
    return hits


def buffers_with_dim_repeated(hlo_text: str, dim: int,
                              times: int = 2) -> int:
    """Count array shapes carrying ``dim`` at least ``times`` times —
    e.g. a ``[.., T, T]`` attention-score square at a distinctive
    sequence extent, which a single-token decode step must never
    build."""
    hits = 0
    for m in _SHAPE_RE.finditer(hlo_text):
        dims = [int(d) for d in m.group(1).split(",") if d]
        if dims.count(dim) >= times:
            hits += 1
    return hits


_DUS_RE = re.compile(r"dynamic-update-slice(?:-start)?\(")
_COPY_RE = re.compile(r"=\s*(?:\([^)]*\)|\S+?\[([0-9,]*)\]\S*)\s*copy\(")


def dynamic_update_slices(hlo_text: str) -> int:
    """Count dynamic-update-slice ops (fused or top-level)."""
    return len(_DUS_RE.findall(hlo_text))


def large_copies_with_dim(hlo_text: str, dim: int, min_volume: int) -> int:
    """Count ``copy`` ops whose result shape carries ``dim`` AND at
    least ``min_volume`` elements — the signature of a full-cache
    round-trip (small layout copies of token-shaped slices pass)."""
    hits = 0
    for m in _COPY_RE.finditer(hlo_text):
        if m.group(1) is None:
            continue
        dims = [int(d) for d in m.group(1).split(",") if d]
        vol = 1
        for d in dims:
            vol *= d
        if dim in dims and vol >= min_volume:
            hits += 1
    return hits


def entry_signature(hlo_text: str) -> str:
    """The ENTRY computation's definition line — every array that is
    live ACROSS the step boundary (donated-in state, fed batch/rng,
    returned state/metrics) appears in this signature; per-layer
    gathers and other step-internal temporaries do not."""
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            return line
    raise ValueError("no ENTRY computation in HLO text")


def compiled_text(jitted, *args) -> str:
    """Optimized (post-SPMD-partitioning) HLO of one jitted program."""
    return jitted.lower(*args).compile().as_text()


def _tiny_trainable():
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import Trainable

    params = {"w": jnp.zeros((16, 4), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))


def _tiny_batch(n: int = 1):
    import numpy as np

    r = np.random.RandomState(0)
    return {"x": r.randn(8, 16).astype(np.float32),
            "y": r.randn(8, 4).astype(np.float32)}


def probe_steps_per_loop(k: int = 4) -> dict:
    """k-step ``run_steps`` program == one module, one loop, the
    single-step program's collective counts (not k×: the scan body is
    not unrolled, so steps-per-loop amortizes dispatch, not compute)."""
    import jax
    from jax import lax

    from autodist_tpu import AllReduce, AutoDist, stack_steps

    spec = {"topology": {"platform": "cpu", "num_devices": 2}}
    runner = AutoDist(spec, AllReduce()).build(_tiny_trainable())
    try:
        step_fn = runner.lowered.step_fn

        def scanned(state, batches, rngs):
            def body(s, xs):
                b, r = xs
                return step_fn(s, b, r)
            return lax.scan(body, state, (batches, rngs))

        stacked = runner.place_steps(stack_steps(
            [_tiny_batch() for _ in range(k)]))
        rngs = jax.random.split(jax.random.PRNGKey(0), k)
        text_k = compiled_text(jax.jit(scanned), runner.state, stacked,
                               rngs)
        text_1 = compiled_text(step_fn, runner.state,
                               runner._place_batch(_tiny_batch()),
                               jax.random.PRNGKey(0))
    finally:
        runner.close()
    counts_k, counts_1 = collective_counts(text_k), collective_counts(text_1)
    has_loop = " while(" in text_k or "while (" in text_k
    assert has_loop, "k-step program lowered without a fused loop"
    assert counts_k == counts_1, (
        f"k-step program changed per-kind collective counts: one step "
        f"{counts_1} vs {k} steps {counts_k} — the scan unrolled")
    return {"k": k, "fused_loop": has_loop,
            "collectives_one_step": counts_1,
            "collectives_k_steps": counts_k}


def probe_single_replica() -> dict:
    """1-device program: the allreduce bypass emits ZERO all-reduce ops
    (and no other cross-device collective either)."""
    import jax

    from autodist_tpu import AllReduce, AutoDist

    spec = {"topology": {"platform": "cpu", "num_devices": 1}}
    runner = AutoDist(spec, AllReduce()).build(_tiny_trainable())
    try:
        text = compiled_text(runner.lowered.step_fn, runner.state,
                             runner._place_batch(_tiny_batch()),
                             jax.random.PRNGKey(0))
    finally:
        runner.close()
    counts = collective_counts(text)
    assert counts["all-reduce"] == 0, (
        f"single-replica step still carries {counts['all-reduce']} "
        "all-reduce op(s)")
    assert sum(counts.values()) == 0, (
        f"single-replica step carries cross-device collectives: {counts}")
    return {"collectives": counts}


def _pipeline_runner(tensor_parallel: int, comm_overlap=None,
                     vocab_parallel: bool = False, vocab_size: int = 32,
                     collective_precision=None):
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=vocab_size, hidden_size=16,
                            num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=8,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    mesh = {"data": 2, "pipe": 2, "model": 2} if tensor_parallel > 1 \
        else {"data": 4, "pipe": 2}
    spec = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": mesh}
    trainable = make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                           jax.random.PRNGKey(0))
    # Hashable policy form (lru_cache): a ("slot", "prec") tuple-of-
    # pairs stands in for the per-boundary dict.
    if isinstance(collective_precision, tuple):
        collective_precision = dict(collective_precision)
    return AutoDist(spec, "Pipeline", num_microbatches=2,
                    tensor_parallel=tensor_parallel,
                    comm_overlap=comm_overlap,
                    vocab_parallel=vocab_parallel,
                    collective_precision=collective_precision
                    ).build(trainable)


import functools


@functools.lru_cache(maxsize=None)
def _pipeline_step_text(tensor_parallel: int, comm_overlap=None,
                        vocab_parallel: bool = False,
                        vocab_size: int = 32,
                        collective_precision=None) -> str:
    """Optimized HLO of one pipeline train step (memoized: the tp=1 and
    blocking tp=2 programs serve both probe_pipeline_tp and
    probe_collective_matmul — each 8-device compile costs tens of
    seconds, and the bench embeds an all-probes run under a budget)."""
    import jax
    import numpy as np

    r = np.random.RandomState(0)
    batch = {"x": r.randint(0, vocab_size, (8, 8)).astype(np.int32),
             "y": r.randint(0, vocab_size, (8, 8)).astype(np.int32)}
    runner = _pipeline_runner(tensor_parallel, comm_overlap,
                              vocab_parallel, vocab_size,
                              collective_precision)
    try:
        return compiled_text(runner.lowered.step_fn, runner.state,
                             runner._place_batch(batch),
                             jax.random.PRNGKey(0))
    finally:
        runner.close()


def probe_pipeline_tp() -> dict:
    """tensor_parallel=2 pipeline step: the stage ring's
    collective-permute is present, and the model-axis activation
    all-reduces appear on top of the tp=1 program's count — at least 4
    more (out-proj + wo forward psums, their custom-VJP backward psums),
    emitted once in the tick-scan body."""
    c1 = collective_counts(_pipeline_step_text(1))
    c2 = collective_counts(_pipeline_step_text(2))
    assert c1["collective-permute"] > 0 and c2["collective-permute"] > 0, (
        f"pipeline ring missing: tp1 {c1} tp2 {c2}")
    extra = c2["all-reduce"] - c1["all-reduce"]
    assert extra >= 4, (
        f"tensor_parallel=2 added only {extra} all-reduce op(s) over "
        f"tp=1 ({c1['all-reduce']} -> {c2['all-reduce']}); expected the "
        "per-stage Megatron activation all-reduces (>= 4)")
    return {"collectives_tp1": c1, "collectives_tp2": c2,
            "model_axis_all_reduces": extra}


def probe_collective_matmul() -> dict:
    """The latency-hiding decomposition (``Pipeline(comm_overlap=...)``)
    at tp=2, against two baselines: the blocking tp=2 program (whose
    model-axis all-reduces must vanish) and the tp=1 program (whose
    all-reduce count the converted program must *equal* — any excess is
    a monolithic model-axis all-reduce that survived or re-fused, any
    shortfall means data/pipe sync went missing).  The ``"matmul"``
    mode must add ≥ tp−1 collective-permute over blocking tp=2 (the
    chunked ring); both modes must emit reduce-scatter + all-gather
    (the decomposed boundary reductions)."""
    tp = 2
    c1 = collective_counts(_pipeline_step_text(1))
    c_blk = collective_counts(_pipeline_step_text(tp))
    report = {"collectives_tp1": c1, "collectives_tp2_blocking": c_blk}
    for mode in ("rsag", "matmul"):
        c = collective_counts(_pipeline_step_text(tp, comm_overlap=mode))
        report[f"collectives_tp2_{mode}"] = c
        assert c["all-reduce"] == c1["all-reduce"], (
            f"comm_overlap={mode!r}: converted tp={tp} program carries "
            f"{c['all-reduce']} all-reduce op(s) vs the tp=1 baseline's "
            f"{c1['all-reduce']} — a monolithic model-axis all-reduce "
            "survived the decomposition (or XLA re-fused the rs+ag pair)")
        assert c["reduce-scatter"] >= 1 and c["all-gather"] >= 1, (
            f"comm_overlap={mode!r}: expected decomposed reduce-scatter/"
            f"all-gather pairs in the converted program, got {c}")
        if mode == "matmul":
            ring_extra = c["collective-permute"] - c_blk["collective-permute"]
            assert ring_extra >= tp - 1, (
                f"collective-matmul ring missing: only {ring_extra} "
                f"collective-permute op(s) over the blocking tp={tp} "
                f"program (expected >= {tp - 1})")
            report["ring_collective_permutes"] = ring_extra
    report["model_axis_all_reduces_removed"] = (
        c_blk["all-reduce"] - c1["all-reduce"])
    return report


def probe_vocab_parallel() -> dict:
    """Vocab parallelism (``Pipeline(vocab_parallel=True)``), the memory
    claim, structurally: at tp=2 the vocab-sharded program's loss head
    never materializes a full-vocab buffer — no array shape in the whole
    optimized per-device module carries the vocab extent V (or its
    zero-padded V_pad; that also rules out a vocab-axis all-gather,
    whose result would be V-sized) — while the replicated tp=2 baseline
    carries the ``[V, H]`` table and ``[.., V]`` logits.  V is chosen so
    no other tensor dimension collides with it (93: odd, so the
    non-divisible zero-pad path compiles too; V_pad=94, shard=47)."""
    V = 93
    V_pad = V + (-V) % 2
    base = collective_counts(_pipeline_step_text(2, vocab_size=V))
    base_full = buffers_with_dim(_pipeline_step_text(2, vocab_size=V), V)
    vp_text = _pipeline_step_text(2, vocab_parallel=True, vocab_size=V)
    vp = collective_counts(vp_text)
    assert base_full > 0, (
        "replicated baseline shows no full-vocab buffer — the probe's "
        "distinctive-dim scan is broken, not proving anything")
    leaks = buffers_with_dim(vp_text, V) + buffers_with_dim(vp_text, V_pad)
    assert leaks == 0, (
        f"vocab-parallel tp=2 program materializes {leaks} full-vocab-"
        f"sized buffer(s) (dim {V}/{V_pad}) — the loss head re-replicated "
        "(or a vocab-axis all-gather assembled the full logits)")
    assert vp["collective-permute"] > 0, (
        f"pipeline ring missing from the vocab-parallel program: {vp}")
    return {"vocab_size": V, "padded_vocab": V_pad,
            "baseline_full_vocab_buffers": base_full,
            "vocab_parallel_full_vocab_buffers": leaks,
            "collectives_baseline": base,
            "collectives_vocab_parallel": vp}


# Distinctive dim of the probe's non-tp stage matrices: no activation,
# batch, or other parameter carries it, so a hit in the ENTRY signature
# IS a full parameter living across the step boundary.
_Z3_DIM = 29
_Z3_V = 2          # virtual stages = per-device layers
_Z3_LEAVES = 3     # ZeRO-3 stage leaves: mix_in, mix_out, wo/bias


def _zero_runner(zero_stage: int, collective_precision=None):
    """dp×pp×tp pipeline (mesh {data:2, pipe:2, model:2}, V=2) whose
    stage has Megatron wi/wo (tp-sharded; their ZeRO requests degrade,
    state shards with the parameter) plus a non-tp ``mix`` pair carrying
    the distinctive :data:`_Z3_DIM` — the variables the ZeRO stage
    actually moves."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist, PipelineTrainable
    from autodist_tpu.parallel.tensor import column_parallel, row_parallel

    HID, FF, C = 8, 16, 4
    r = np.random.RandomState(0)
    stacked = {
        "wi": {"kernel": jnp.asarray(r.randn(C, HID, FF) * 0.3,
                                     jnp.float32),
               "bias": jnp.zeros((C, FF), jnp.float32)},
        "wo": {"kernel": jnp.asarray(r.randn(C, FF, HID) * 0.3,
                                     jnp.float32),
               "bias": jnp.zeros((C, HID), jnp.float32)},
        "mix_in": jnp.asarray(r.randn(C, HID, _Z3_DIM) * 0.3, jnp.float32),
        "mix_out": jnp.asarray(r.randn(C, _Z3_DIM, HID) * 0.3, jnp.float32),
    }

    def stage_fn(p, x, model_axis=None, comm_overlap=None):
        h = jax.nn.relu(column_parallel(x, p["wi"]["kernel"],
                                        p["wi"]["bias"],
                                        model_axis=model_axis))
        y = row_parallel(h, p["wo"]["kernel"], p["wo"]["bias"],
                         model_axis=model_axis)
        return y + jnp.tanh(y @ p["mix_in"]) @ p["mix_out"]

    def head(outputs, batch):
        return jnp.mean((outputs - batch["y"]) ** 2), {}

    trainable = PipelineTrainable(stage_fn, stacked, head, optax.adam(1e-2),
                                  num_stages=C)
    spec = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 2, "pipe": 2, "model": 2}}
    if isinstance(collective_precision, tuple):
        collective_precision = dict(collective_precision)
    return AutoDist(spec, "Pipeline", num_microbatches=2,
                    virtual_stages=_Z3_V, tensor_parallel=2,
                    zero_stage=zero_stage,
                    collective_precision=collective_precision
                    ).build(trainable)


@functools.lru_cache(maxsize=None)
def _zero_step_text(zero_stage: int, collective_precision=None) -> str:
    import jax
    import numpy as np

    r = np.random.RandomState(0)
    batch = {"x": r.randn(8, 8).astype(np.float32),
             "y": r.randn(8, 8).astype(np.float32)}
    runner = _zero_runner(zero_stage, collective_precision)
    try:
        return compiled_text(runner.lowered.step_fn, runner.state,
                             runner._place_batch(batch),
                             jax.random.PRNGKey(0))
    finally:
        runner.close()


def probe_zero3() -> dict:
    """ZeRO-2/3 on the tp×dp pipeline, structurally: the stage-3
    program stores parameters ONLY as flat shards across the step
    boundary (zero ENTRY-signature buffers of the distinctive extent,
    vs. the stage-0 baseline whose state carries them — a re-gather of
    full storage, or a re-materialization surviving into the returned
    state, fails here) while emitting >= one all-gather per (layer,
    leaf) — the per-layer on-demand gathers; a combiner pass collapsing
    them into one bulk up-front gather drops the count below
    layers x leaves and fails.  Stage 2 syncs gradients by
    reduce-scatter where the stage-0 baseline emits none."""
    t0 = _zero_step_text(0)
    t2 = _zero_step_text(2)
    t3 = _zero_step_text(3)
    c0, c2, c3 = map(collective_counts, (t0, t2, t3))
    boundary0 = buffers_with_dim(entry_signature(t0), _Z3_DIM)
    boundary3 = buffers_with_dim(entry_signature(t3), _Z3_DIM)
    assert boundary0 > 0, (
        "stage-0 baseline shows no full-parameter buffer at the step "
        "boundary — the probe's distinctive-dim scan is broken, not "
        "proving anything")
    assert boundary3 == 0, (
        f"stage-3 program carries {boundary3} full-parameter buffer(s) "
        f"(dim {_Z3_DIM}) across the step boundary — parameters must "
        "live only as ZeRO shards between steps")
    min_gathers = _Z3_V * _Z3_LEAVES
    assert c3["all-gather"] >= min_gathers, (
        f"stage-3 program emits {c3['all-gather']} all-gather(s); "
        f"expected >= {min_gathers} (one per (virtual stage, leaf)) — "
        "the per-layer gathers collapsed into a bulk materialization")
    assert c3["reduce-scatter"] >= 1, (
        f"stage-3 program emits no reduce-scatter: {c3} — the gather's "
        "custom VJP should scatter gradients into shard form")
    assert c0["reduce-scatter"] == 0, (
        f"stage-0 baseline unexpectedly reduce-scatters: {c0}")
    assert c2["reduce-scatter"] >= 1, (
        f"stage-2 program syncs gradients without a reduce-scatter: "
        f"{c2} — the ZeRO grad sync regressed to an all-reduce")
    return {"distinctive_dim": _Z3_DIM,
            "boundary_full_param_buffers_stage0": boundary0,
            "boundary_full_param_buffers_stage3": boundary3,
            "min_per_layer_gathers": min_gathers,
            "collectives_stage0": c0,
            "collectives_stage2": c2,
            "collectives_stage3": c3}


# Decode-probe geometry: T (cache max_len) and V (vocab) are chosen
# distinctive — no other tensor dimension equals either, so a shape scan
# hit IS the buffer the claim forbids.
_DEC_T = 57
_DEC_V = 93
_DEC_LAYERS = 2
_DEC_SLOTS = 3


@functools.lru_cache(maxsize=None)
def _decode_step_text(tensor_parallel: int, vocab_parallel: bool) -> str:
    """Optimized HLO of one fused-decode dispatch of the serving
    engine (memoized like the pipeline texts)."""
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.serving import ServingEngine

    cfg = TransformerConfig(vocab_size=_DEC_V, hidden_size=16,
                            num_layers=_DEC_LAYERS, num_heads=2,
                            mlp_dim=32, max_len=_DEC_T, dtype=jnp.float32,
                            dropout_rate=0.0, attention_dropout_rate=0.0)
    params = make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(0)).params
    engine = ServingEngine(cfg, params, tensor_parallel=tensor_parallel,
                           vocab_parallel=vocab_parallel,
                           num_slots=_DEC_SLOTS, max_len=_DEC_T,
                           prefill_len=8, decode_steps=4)
    return engine.compiled_decode_text()


def probe_decode() -> dict:
    """The serving engine's decode-step memory/dispatch claims,
    structurally: the vocab-parallel tp=2 program carries ZERO
    full-vocab buffers (vs the tp=1 baseline, which carries the ``[V,H]``
    table and ``[B,V]`` logits — the scan-validity control); neither
    program builds a ``[T, T]`` attention-score square (decode scores
    live at ``[B, heads, 1, T]``); the KV cache updates via in-place
    ``dynamic-update-slice`` (>= 2 per layer: k and v) with the cache
    buffers donated/aliased and no full-cache-sized copy anywhere; and
    the K-token window is ONE module with a fused ``while`` loop — one
    dispatch per K tokens, the ``run_steps`` property at decode time."""
    tp = 2
    base = _decode_step_text(1, False)
    vp = _decode_step_text(tp, True)
    V_pad = _DEC_V + (-_DEC_V) % tp
    base_full = buffers_with_dim(base, _DEC_V)
    assert base_full > 0, (
        "tp=1 baseline decode shows no full-vocab buffer — the probe's "
        "distinctive-dim scan is broken, not proving anything")
    leaks = buffers_with_dim(vp, _DEC_V) + buffers_with_dim(vp, V_pad)
    assert leaks == 0, (
        f"vocab-parallel decode materializes {leaks} full-vocab-sized "
        f"buffer(s) (dim {_DEC_V}/{V_pad}) — the greedy epilogue "
        "re-replicated (or a vocab-axis all-gather assembled the logits)")
    report = {"vocab_size": _DEC_V, "max_len": _DEC_T,
              "baseline_full_vocab_buffers": base_full,
              "vocab_parallel_full_vocab_buffers": leaks}
    # one layer's cache lane [slots, heads_local, T, head_dim] is the
    # smallest buffer a "full-cache copy" could round-trip
    cfg_head_dim = 8
    for name, text, heads_local in (("tp1", base, 2), ("vp", vp, 1)):
        squares = buffers_with_dim_repeated(text, _DEC_T)
        assert squares == 0, (
            f"{name} decode builds {squares} [{_DEC_T}, {_DEC_T}]-extent "
            "buffer(s) — a full-sequence attention-score square in a "
            "single-token step")
        dus = dynamic_update_slices(text)
        assert dus >= 2 * _DEC_LAYERS, (
            f"{name} decode emits only {dus} dynamic-update-slice(s); "
            f"expected >= {2 * _DEC_LAYERS} (k and v per layer) — the "
            "KV write lowered to something else (scatter/concat)")
        lane_n = _DEC_SLOTS * heads_local * _DEC_T * cfg_head_dim
        cache_copies = large_copies_with_dim(text, _DEC_T, lane_n)
        assert cache_copies == 0, (
            f"{name} decode copies {cache_copies} cache-lane-sized "
            f"buffer(s) per dispatch — the in-place update regressed "
            "to copy-on-write")
        assert " while(" in text or "while (" in text, (
            f"{name} decode lowered without a fused loop — K token "
            "steps are dispatching separately")
        assert "input_output_alias" in text, (
            f"{name} decode carries no input/output aliasing — the "
            "donated KV cache is being re-allocated every dispatch")
        report[f"dynamic_update_slices_{name}"] = dus
        report[f"collectives_{name}"] = collective_counts(text)
    assert report["collectives_vp"]["all-reduce"] >= 2 * _DEC_LAYERS, (
        "vocab-parallel tp=2 decode misses the per-layer Megatron "
        f"boundary all-reduces: {report['collectives_vp']}")
    assert sum(report["collectives_tp1"].values()) == 0, (
        f"tp=1 decode carries collectives: {report['collectives_tp1']}")
    return report


def probe_quantized() -> dict:
    """The per-collective precision policy, structurally: quantization
    happens *inside* the program — convert-before, narrowed collective
    operand dtype, convert-after — exactly at the policied boundaries.

    * fp32 policy (the default) carries ZERO narrowed collectives — a
      lowering that silently narrows an un-policied boundary fails.
    * ``tp_psum=int8`` at blocking tp=2 carries >= 4 narrowed
      all-reduces (the Megatron out/wo forward psums and qkv/wi backward
      cotangent psums, on an fp16 levels wire) with the matching
      f16-in/f32-out convert pairs — while the dp grad sync, NOT
      policied in this program, keeps its payload-carrying fp32
      all-reduces (narrowing is per-boundary, not per-program).
    * ``tp_psum=int8`` + ``comm_overlap=rsag``: the decomposed pair
      stays un-re-fused (payload-carrying all-reduce count equals the
      tp=1 baseline's — the shared-scale pmaxes a quantized boundary
      adds are scalar and counted separately) and both halves narrow:
      the rs sums int8 levels on fp16, the ag rides a TRUE s8 wire.
    * full ``int8`` policy at zero_stage=3: the per-layer on-demand
      gathers carry narrowed payloads (>= one per (virtual stage,
      leaf)) and the backward cotangent reduce-scatter narrows too.
    """
    tp = 2
    fp32_text = _pipeline_step_text(tp)
    n_fp32 = narrowed_collective_counts(fp32_text)
    assert sum(n_fp32.values()) == 0, (
        f"fp32-policy tp={tp} program carries narrowed collectives: "
        f"{n_fp32} — an un-policied boundary silently narrowed")

    tp_only = (("tp_psum", "int8"),)
    q_text = _pipeline_step_text(tp, collective_precision=tp_only)
    n_q = narrowed_collective_counts(q_text)
    assert n_q["all-reduce"] >= 4, (
        f"tp_psum=int8 narrowed only {n_q['all-reduce']} all-reduce "
        "op(s); expected >= 4 (out/wo forward + qkv/wi backward psums "
        "on the fp16 levels wire)")
    conv = convert_counts(q_text)
    assert conv.get("f16", 0) >= n_q["all-reduce"], (
        f"missing convert-before halves: {conv} vs {n_q['all-reduce']} "
        "narrowed all-reduces")
    assert conv.get("f32", 0) >= 1, (
        f"missing convert-after halves (back to f32): {conv}")
    big_f32_ars = sum(1 for kind, dt, elems in collective_wire(q_text)
                      if kind == "all-reduce" and dt == "f32"
                      and elems > 1)
    assert big_f32_ars >= 1, (
        "tp_psum-only int8 policy narrowed the (un-policied) dp grad "
        "sync too — fp32 boundaries must stay untouched")

    c1_payload = nonscalar_all_reduces(_pipeline_step_text(1))
    rsag_text = _pipeline_step_text(tp, comm_overlap="rsag",
                                    collective_precision=tp_only)
    n_rsag = narrowed_collective_counts(rsag_text)
    rsag_payload = nonscalar_all_reduces(rsag_text)
    assert rsag_payload == c1_payload, (
        f"quantized rs+ag program carries {rsag_payload} payload "
        f"all-reduce(s) vs the tp=1 baseline's {c1_payload} — a "
        "monolithic model-axis all-reduce survived or the pair re-fused")
    assert n_rsag["reduce-scatter"] >= 1, (
        f"no narrowed reduce-scatter in the quantized rs+ag program: "
        f"{n_rsag}")
    assert n_rsag["all-gather"] >= 1, (
        f"no narrowed all-gather in the quantized rs+ag program: "
        f"{n_rsag}")
    s8_ags = sum(1 for kind, dt, _ in collective_wire(rsag_text)
                 if kind == "all-gather" and dt == "s8")
    assert s8_ags >= 1, (
        "the ag half of the quantized pair is not on a true s8 wire")

    z3_text = _zero_step_text(3, "int8")
    n_z3 = narrowed_collective_counts(z3_text)
    min_gathers = _Z3_V * _Z3_LEAVES
    assert n_z3["all-gather"] >= min_gathers, (
        f"int8 zero_stage=3 program narrows only {n_z3['all-gather']} "
        f"all-gather(s); expected >= {min_gathers} (one per (virtual "
        "stage, leaf))")
    assert n_z3["reduce-scatter"] >= 1, (
        f"int8 zero3 backward cotangent reduce-scatter not narrowed: "
        f"{n_z3}")
    return {"narrowed_fp32_policy": n_fp32,
            "narrowed_tp_psum_int8": n_q,
            "converts_tp_psum_int8": {k: conv[k] for k in ("f16", "f32")
                                      if k in conv},
            "payload_f32_all_reduces_tp_psum_int8": big_f32_ars,
            "payload_all_reduces_tp1": c1_payload,
            "payload_all_reduces_rsag_int8": rsag_payload,
            "narrowed_rsag_int8": n_rsag,
            "s8_all_gathers_rsag_int8": s8_ags,
            "narrowed_zero3_int8": n_z3,
            "min_per_layer_gathers": min_gathers}


PROBES = {
    "steps_per_loop": probe_steps_per_loop,
    "single_replica": probe_single_replica,
    "pipeline_tp": probe_pipeline_tp,
    "collective_matmul": probe_collective_matmul,
    "vocab_parallel": probe_vocab_parallel,
    "zero3": probe_zero3,
    "quantized": probe_quantized,
    "decode": probe_decode,
}


def run_probes(names=None) -> tuple[dict, list]:
    """Run the named probes (default all); returns (report, failed)."""
    report, failed = {}, []
    for name in (names or list(PROBES)):
        try:
            report[name] = {"ok": True, **PROBES[name]()}
        except AssertionError as e:
            report[name] = {"ok": False, "error": str(e)}
            failed.append(name)
    return report, failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="HLO-structural proof of collective claims (CPU mesh)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the report to this file (machine-"
                         "readable provenance — bench.py embeds it)")
    ap.add_argument("--probe", action="append", choices=sorted(PROBES),
                    help="run only these probes (repeatable; default all)")
    args = ap.parse_args(argv)
    report, failed = run_probes(args.probe)
    text = json.dumps(report, indent=2)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    print(text)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
