"""HLO-structural proof of the framework's performance claims — on CPU.

Round-5 VERDICT demanded silicon-free falsifiability: every "we emit
fewer/better collectives" claim must be checkable without the flaky TPU
tunnel.  This probe lowers real train-step programs with
``jax.jit(...).lower(...).compile()`` on simulated CPU meshes and
asserts collective *counts and kinds* in the optimized HLO text.

This module is now a thin back-compat shim: the facts layer lives in
:mod:`autodist_tpu.analysis.facts`, the memoized program corpus in
:mod:`autodist_tpu.analysis.programs`, the declarative rules in
:mod:`autodist_tpu.analysis.program_rules`, and the probes themselves —
identical names, reports, and pass/fail behavior — in
:mod:`autodist_tpu.analysis.probes`.  The same engine also powers
``tools/lint_strategy.py``, which sweeps the ENTIRE AutoStrategy zoo
(plan lint + program lint) instead of these eight hand-picked programs.

* ``probe_steps_per_loop`` — ``run_steps``'s k-step program is ONE HLO
  module whose scan is a ``while`` loop with the *same* collective
  counts as the single-step program.
* ``probe_single_replica`` — a 1-device program contains zero
  cross-device collectives (the allreduce bypass).
* ``probe_pipeline_tp`` — tensor_parallel=2 adds the per-stage
  Megatron activation all-reduces on top of the tp=1 program.
* ``probe_collective_matmul`` — the latency-hiding decomposition
  removes every monolithic model-axis all-reduce without re-fusion.
* ``probe_vocab_parallel`` — the vocab-sharded program materializes no
  full-vocab buffer anywhere.
* ``probe_quantized`` — the per-collective precision policy narrows
  exactly the policied boundaries' wire dtypes.
* ``probe_decode`` — the serving decode window is buffer-clean,
  in-place, and one fused dispatch per K tokens.
* ``probe_zero3`` — ZeRO-3 stores parameters only as shards across the
  step boundary, gathering per layer on demand.

Run as a script for a JSON report::

    JAX_PLATFORMS=cpu python tools/hlo_probe.py            # all probes
    JAX_PLATFORMS=cpu python tools/hlo_probe.py --json out.json
    JAX_PLATFORMS=cpu python tools/hlo_probe.py --probe single_replica
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":  # simulated mesh before the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from autodist_tpu.analysis.facts import (buffers_with_dim,  # noqa: E402,F401
                                         buffers_with_dim_repeated,
                                         collective_counts,
                                         collective_wire, compiled_text,
                                         convert_counts,
                                         dynamic_update_slices,
                                         entry_signature,
                                         large_copies_with_dim,
                                         narrowed_collective_counts,
                                         nonscalar_all_reduces)
from autodist_tpu.analysis.probes import (PROBES,  # noqa: E402,F401
                                          probe_collective_matmul,
                                          probe_decode,
                                          probe_pipeline_tp,
                                          probe_quantized,
                                          probe_single_replica,
                                          probe_steps_per_loop,
                                          probe_vocab_parallel,
                                          probe_zero3, run_probes)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="HLO-structural proof of collective claims (CPU mesh)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the report to this file (machine-"
                         "readable provenance — bench.py embeds it)")
    ap.add_argument("--probe", action="append", choices=sorted(PROBES),
                    help="run only these probes (repeatable; default all)")
    args = ap.parse_args(argv)
    report, failed = run_probes(args.probe)
    text = json.dumps(report, indent=2)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    print(text)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
