#!/bin/bash
# Hardware measurement session: wait for a healthy TPU tunnel, then run the
# full measurement queue STRICTLY SERIALLY.
#
# Why this exists (operational discipline, learned round 4):
#   * The axon tunnel exposes ONE real chip and behaves as effectively
#     single-client. Two processes initializing PJRT concurrently can make
#     one fail with `UNAVAILABLE` or hang inside client init (an
#     un-interruptible C call). Round 4's only healthy window was lost to
#     exactly this: a probe loop running alongside bench.py.
#   * Therefore: one probe at a time, long sleeps between probes, and once
#     a probe succeeds the queue owns the tunnel until it finishes. Nothing
#     else on the host may touch the tunnel while this script runs.
#   * All local/CPU work must run with the tunnel dial disabled:
#       env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python ...
#     (sitecustomize gates the relay dial on PALLAS_AXON_POOL_IPS; env alone
#     is not enough to pin the backend — tools also call
#     jax.config.update("jax_platforms", "cpu") right after import jax,
#     because the plugin pins the backend at interpreter start.)
#   * First TPU compile is multi-minute; every timeout below budgets for a
#     cold compile cache. bench.py carries its own watchdog subprocess so a
#     PJRT-init hang is reported rather than blocking forever.
#
# Queue (in dependency order — the bench result gates the rest so an
# illusory one-probe window does not burn the queue).  Learned on round
# 5's first window: under a degraded tunnel every compile is 10+ minutes
# and can fail transiently, so each step must land its headline number
# off ONE compile — bench.py scores first and tunes opportunistically,
# and the ResNet run pins its batch (64/chip, safely inside v5e HBM)
# instead of the 3-compile self-tune probe chain:
#   1. bench.py                      -> /tmp/hw_bench.json      (headline MFU)
#   2. examples/benchmark/imagenet.py --batch-size (64*chips)
#                                     -> /tmp/hw_resnet50.out   (images/sec/chip)
#   3. tools/flash_crossover.py --causal --write flash_tuning.json
#                                     -> /tmp/hw_flash_causal.out
#   4. tools/flash_crossover.py --write flash_tuning.json (non-causal)
#                                     -> /tmp/hw_flash_noncausal.out
#   5. tools/calibrate_compressors.py -> /tmp/hw_calib.out      (calibration.json input)
# Afterwards: record results in BASELINE.md; COMMIT calibration.json AND
# flash_tuning.json (the kernel's default block sizes and the bench's
# flash-vs-einsum choice read the committed table).
LOG=${HW_SESSION_LOG:-/tmp/hw_session.log}
# HW_SESSION_DEADLINE (epoch seconds): exit before it so this watcher can
# never contend with an externally launched bench (e.g. the round driver's
# end-of-round bench.py run) — the single-client lesson of round 4.
DEADLINE=${HW_SESSION_DEADLINE:-0}
echo "$(date -u +%H:%M:%S) session start (deadline=$DEADLINE)" >> "$LOG"
cd "$(dirname "$0")/.."

# have_time BUDGET: true iff a step bounded by BUDGET seconds finishes
# before the deadline.  Checked before EVERY queue step, not just at the
# top of the loop — a queue that starts near the deadline must stop
# between steps rather than overrun it by hours.
have_time() {
  [ "$DEADLINE" -le 0 ] && return 0
  [ $(( $(date +%s) + $1 )) -lt "$DEADLINE" ]
}

while true; do
  if ! have_time 130; then
    echo "$(date -u +%H:%M:%S) deadline reached — exiting" >> "$LOG"
    exit 0
  fi
  # Probe = a real (tiny) compile + execute, not just device enumeration:
  # observed 2026-07-31, `jax.devices()` can succeed while the tunnel's
  # remote-compile endpoint refuses connections — enumeration alone calls
  # a window healthy that cannot run a single step.
  if timeout 180 python -c "import jax, jax.numpy as jnp; jax.jit(lambda x: (x * 2).sum())(jnp.ones((128, 128))).block_until_ready()" >/dev/null 2>&1; then
    if ! have_time 2510; then
      echo "$(date -u +%H:%M:%S) healthy but no time for bench — exiting" >> "$LOG"
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel healthy — starting queue" >> "$LOG"
    AUTODIST_TPU_BENCH_PROFILE=/tmp/hw_profile       timeout 2500 python bench.py > /tmp/hw_bench.json 2>/tmp/hw_bench.err
    echo "$(date -u +%H:%M:%S) bench rc=$? $(tail -c 300 /tmp/hw_bench.json)" >> "$LOG"
    # Only continue if the bench actually produced a measurement (no
    # "error" key and a nonzero value — bench.py emits value 0.0 exactly
    # when the backend was unavailable); otherwise the window was
    # illusory; go back to waiting.  A low-but-real MFU still advances
    # the queue: calibration/crossover validity doesn't depend on it.
    # Every later step re-checks the deadline (have_time) so a queue
    # that started late stops BETWEEN steps instead of overrunning into
    # an externally launched bench.
    if ! grep -q '"error"' /tmp/hw_bench.json \
        && grep -q '"value"' /tmp/hw_bench.json \
        && ! grep -q '"value": 0\.0[,}]' /tmp/hw_bench.json; then
      have_time 1810 || { echo "$(date -u +%H:%M:%S) deadline — stop after bench" >> "$LOG"; exit 0; }
      # One pinned batch = one compile; 64/chip sits safely inside v5e
      # HBM for ResNet-50 + SGD-momentum while filling the MXU well.
      CHIPS=$(timeout 180 python -c "import jax; print(len(jax.devices()))" 2>/dev/null)
      [ -n "$CHIPS" ] || CHIPS=1
      timeout 1800 python examples/benchmark/imagenet.py --model resnet50 \
        --batch-size $((64 * CHIPS)) --train-steps 30 --warmup-steps 3 --json \
        > /tmp/hw_resnet50.out 2>/tmp/hw_resnet50.err
      echo "$(date -u +%H:%M:%S) resnet50 rc=$?" >> "$LOG"
      have_time 1510 || { echo "$(date -u +%H:%M:%S) deadline — stop after resnet" >> "$LOG"; exit 0; }
      timeout 1500 python tools/flash_crossover.py --causal \
        --write flash_tuning.json \
        > /tmp/hw_flash_causal.out 2>/tmp/hw_flash_causal.err
      echo "$(date -u +%H:%M:%S) flash-causal rc=$?" >> "$LOG"
      have_time 1510 || { echo "$(date -u +%H:%M:%S) deadline — stop after flash-causal" >> "$LOG"; exit 0; }
      timeout 1500 python tools/flash_crossover.py \
        --write flash_tuning.json \
        > /tmp/hw_flash_noncausal.out 2>/tmp/hw_flash_noncausal.err
      echo "$(date -u +%H:%M:%S) flash-noncausal rc=$?" >> "$LOG"
      have_time 1510 || { echo "$(date -u +%H:%M:%S) deadline — stop after flash" >> "$LOG"; exit 0; }
      timeout 1500 python tools/calibrate_compressors.py \
        > /tmp/hw_calib.out 2>/tmp/hw_calib.err
      echo "$(date -u +%H:%M:%S) calib rc=$?" >> "$LOG"
      echo "$(date -u +%H:%M:%S) queue complete" >> "$LOG"
      exit 0
    fi
  else
    echo "$(date -u +%H:%M:%S) probe failed" >> "$LOG"
  fi
  sleep 480
done
