#!/bin/bash
# Hardware measurement session: wait for a healthy TPU tunnel, then run the
# full measurement queue STRICTLY SERIALLY.
#
# Why this exists (operational discipline, learned round 4):
#   * The axon tunnel exposes ONE real chip and behaves as effectively
#     single-client. Two processes initializing PJRT concurrently can make
#     one fail with `UNAVAILABLE` or hang inside client init (an
#     un-interruptible C call). Round 4's only healthy window was lost to
#     exactly this: a probe loop running alongside bench.py.
#   * Therefore: one probe at a time, long sleeps between probes, and once
#     a probe succeeds the queue owns the tunnel until it finishes. Nothing
#     else on the host may touch the tunnel while this script runs.
#   * All local/CPU work must run with the tunnel dial disabled:
#       env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python ...
#     (sitecustomize gates the relay dial on PALLAS_AXON_POOL_IPS; env alone
#     is not enough to pin the backend — tools also call
#     jax.config.update("jax_platforms", "cpu") right after import jax,
#     because the plugin pins the backend at interpreter start.)
#   * First TPU compile is multi-minute; every timeout below budgets for a
#     cold compile cache. bench.py carries its own watchdog subprocess so a
#     PJRT-init hang is reported rather than blocking forever.
#
# Queue (in dependency order — the bench result gates the rest so an
# illusory one-probe window does not burn the queue):
#   1. bench.py                      -> /tmp/hw_bench.json      (headline MFU)
#   2. examples/benchmark/imagenet.py -> /tmp/hw_resnet50.out   (images/sec/chip)
#   3. tools/calibrate_compressors.py -> /tmp/hw_calib.out      (calibration.json input)
#   4. tools/flash_crossover.py --causal --write flash_tuning.json
#                                     -> /tmp/hw_flash_causal.out
#   5. tools/flash_crossover.py --write flash_tuning.json (non-causal)
#                                     -> /tmp/hw_flash_noncausal.out
# Afterwards: record results in BASELINE.md; COMMIT calibration.json AND
# flash_tuning.json (the kernel's default block sizes and the bench's
# flash-vs-einsum choice read the committed table).
LOG=${HW_SESSION_LOG:-/tmp/hw_session.log}
echo "$(date -u +%H:%M:%S) session start" >> "$LOG"
cd "$(dirname "$0")/.."
while true; do
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel healthy — starting queue" >> "$LOG"
    timeout 2500 python bench.py > /tmp/hw_bench.json 2>/tmp/hw_bench.err
    echo "$(date -u +%H:%M:%S) bench rc=$? $(tail -c 300 /tmp/hw_bench.json)" >> "$LOG"
    # Only continue if the bench actually produced a measurement (no
    # "error" key and a nonzero value — bench.py emits value 0.0 exactly
    # when the backend was unavailable); otherwise the window was
    # illusory; go back to waiting.  A low-but-real MFU still advances
    # the queue: calibration/crossover validity doesn't depend on it.
    if ! grep -q '"error"' /tmp/hw_bench.json \
        && grep -q '"value"' /tmp/hw_bench.json \
        && ! grep -q '"value": 0\.0[,}]' /tmp/hw_bench.json; then
      timeout 1800 python examples/benchmark/imagenet.py --model resnet50 \
        --train-steps 30 --warmup-steps 3 --json \
        > /tmp/hw_resnet50.out 2>/tmp/hw_resnet50.err
      echo "$(date -u +%H:%M:%S) resnet50 rc=$?" >> "$LOG"
      timeout 1500 python tools/calibrate_compressors.py \
        > /tmp/hw_calib.out 2>/tmp/hw_calib.err
      echo "$(date -u +%H:%M:%S) calib rc=$?" >> "$LOG"
      timeout 1500 python tools/flash_crossover.py --causal \
        --write flash_tuning.json \
        > /tmp/hw_flash_causal.out 2>/tmp/hw_flash_causal.err
      echo "$(date -u +%H:%M:%S) flash-causal rc=$?" >> "$LOG"
      timeout 1500 python tools/flash_crossover.py \
        --write flash_tuning.json \
        > /tmp/hw_flash_noncausal.out 2>/tmp/hw_flash_noncausal.err
      echo "$(date -u +%H:%M:%S) flash-noncausal rc=$?" >> "$LOG"
      echo "$(date -u +%H:%M:%S) queue complete" >> "$LOG"
      exit 0
    fi
  else
    echo "$(date -u +%H:%M:%S) probe failed" >> "$LOG"
  fi
  sleep 480
done
