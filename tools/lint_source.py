"""AST repo lint: raw collectives must route through the policy layer.

PR 8 gave every collective boundary a per-collective precision slot —
but only because each lowering routes its collectives through the
sanctioned wrappers (``parallel/tensor.py``'s ``precision_scope``
primitives, ``kernel/``'s ``zero3_gather``/quantize/compressor
helpers).  A new lowering calling ``lax.psum`` / ``lax.all_gather`` /
``lax.psum_scatter`` directly would silently bypass the policy (and the
cost model's wire accounting), so this linter forbids raw calls outside
the sanctioned modules:

* ``autodist_tpu/parallel/tensor.py`` — the precision primitives
* ``autodist_tpu/kernel/`` — the quantize/compressor/gather layer
* ``autodist_tpu/_jax_compat.py`` — the version shim

A deliberate exception (a collective that is *not* a policied data
boundary — e.g. the pipeline's pipe-axis role reductions) carries an
inline pragma on the call line or the line above::

    gp = lax.psum(g, pipe_axis)  # lint: allow-raw-collective — <why>

Violations are ``ADT201`` diagnostics (file:line); rc 1 on any.
Tier-1 runs this over ``autodist_tpu/`` so the rule holds for every
future lowering.

    python tools/lint_source.py            # lint autodist_tpu/
    python tools/lint_source.py --check    # CI spelling (compact)
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

# Raw collective calls that must route through the policy layer.
FORBIDDEN = ("psum", "all_gather", "psum_scatter")

# Modules allowed to touch lax collectives directly (repo-relative,
# forward slashes; directories end with "/").
ALLOWED = ("autodist_tpu/parallel/tensor.py",
           "autodist_tpu/kernel/",
           "autodist_tpu/_jax_compat.py")

PRAGMA = "lint: allow-raw-collective"

FIX = ("route through autodist_tpu.parallel.tensor (precision_scope "
       "primitives) or kernel/ helpers (zero3_gather, quantize), or "
       f"annotate '# {PRAGMA} — <reason>' for a non-policied boundary")


def _lax_aliases(tree: ast.AST) -> tuple[dict, set]:
    """Every local spelling of a forbidden collective in this module:
    ``(bare_names, module_aliases)`` where ``bare_names`` maps a local
    name to the collective it binds (``from jax.lax import psum as p``)
    and ``module_aliases`` holds every name bound to the lax module
    (``from jax import lax``, ``import jax.lax as jl``)."""
    bare: dict[str, str] = {}
    modules: set[str] = {"lax"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module in ("jax.lax", "jax._src.lax"):
                for a in node.names:
                    if a.name in FORBIDDEN:
                        bare[a.asname or a.name] = a.name
            elif node.module == "jax":
                for a in node.names:
                    if a.name == "lax":
                        modules.add(a.asname or "lax")
        elif isinstance(node, ast.Import):
            for a in node.names:
                # `import jax.lax as jl` -> jl.psum; the un-aliased
                # `import jax.lax` form calls jax.lax.psum, which the
                # attribute-chain branch below already catches.
                if a.name == "jax.lax" and a.asname:
                    modules.add(a.asname)
    return bare, modules


def _is_lax_collective(node: ast.Call, bare: dict, modules: set):
    """``lax.psum(...)`` / ``jax.lax.psum(...)`` / aliased-module /
    from-imported spellings of a forbidden collective; returns the
    dotted name or None."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in bare:
        return bare[fn.id]
    if not isinstance(fn, ast.Attribute) or fn.attr not in FORBIDDEN:
        return None
    base = fn.value
    if isinstance(base, ast.Name) and base.id in modules:
        return f"{base.id}.{fn.attr}"
    if isinstance(base, ast.Attribute) and base.attr == "lax":
        return f"jax.lax.{fn.attr}"
    return None


def lint_file(path: str, rel: str) -> list:
    """ADT201 diagnostics for one file (empty = clean)."""
    from autodist_tpu.analysis.diagnostics import Diagnostic

    try:
        source = open(path).read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        return [Diagnostic("ADT201", f"unparseable: {e}", where=rel)]
    lines = source.splitlines()
    bare, modules = _lax_aliases(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _is_lax_collective(node, bare, modules)
        if name is None:
            continue
        ln = node.lineno
        context = " ".join(lines[max(ln - 2, 0):ln])
        if PRAGMA in context:
            continue
        out.append(Diagnostic(
            "ADT201",
            f"raw {name}() in a lowering module bypasses the "
            "per-collective precision policy",
            where=f"{rel}:{ln}", fix=FIX, rule="no_raw_collective"))
    return out


def lint_tree(root: str) -> list:
    """Lint every .py under ``root`` (package-relative allowlist)."""
    diags = []
    root = os.path.abspath(root)
    repo = os.path.dirname(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            if any(rel == a or (a.endswith("/") and rel.startswith(a))
                   for a in ALLOWED):
                continue
            diags.extend(lint_file(path, rel))
    return diags


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="forbid raw lax collectives outside the policy "
                    "layer (ADT201)")
    ap.add_argument("--root", default=None,
                    help="package root to lint (default: the repo's "
                         "autodist_tpu/)")
    ap.add_argument("--json", metavar="PATH", default=None)
    ap.add_argument("--check", action="store_true",
                    help="CI spelling: compact output, same rc")
    args = ap.parse_args(argv)
    root = args.root or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "autodist_tpu")
    diags = lint_tree(root)
    if args.json:
        with open(args.json, "w") as f:
            json.dump([d.to_dict() for d in diags], f, indent=1)
    if diags:
        for d in diags:
            print(d)
        print(f"{len(diags)} raw-collective violation(s)")
        return 1
    if not args.check:
        print(f"source lint clean ({root})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
