"""Sweep the AutoStrategy zoo (and serialized plans) through the linter.

Three modes, composable::

    # every AutoStrategy candidate x {train, decode}: plan lint, then
    # lower + compile on the simulated CPU mesh and program-lint the
    # optimized HLO — fails (rc 1) on any ADT ERROR
    JAX_PLATFORMS=cpu python tools/lint_strategy.py --zoo

    # the mutation-test harness: prove every shipped rule fires on its
    # seeded violation (and stays silent on the honest artifact)
    JAX_PLATFORMS=cpu python tools/lint_strategy.py --mutate

    # plan-lint serialized strategy JSON files (hand-edited plans)
    python tools/lint_strategy.py /path/to/strategy.json

``--check`` is the CI spelling (compact output, same rc contract);
``--plan-only`` skips the program compiles; ``--max-programs N`` is the
CI budget guard — plan lint still covers every candidate, and every
program the cap drops is listed (no silent truncation).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":  # simulated mesh before the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# Distinctive vocab for the zoo's LM fixture: the program-lint
# full-vocab rule needs an extent no other tensor dimension equals
# (odd, so the zero-pad path compiles too).
ZOO_VOCAB = 93


def _zoo_fixtures():
    """The trainable/topology pairs the candidate zoo builds against:
    the tiny data-parallel trainable (AllReduce/PS/ZeRO/gspmd families)
    and the stage-structured pipeline LM on the 3-axis mesh (every
    Pipeline variant).  Yields ``(fixture_name, trainable, spec,
    batch)``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu.analysis import programs
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec

    yield ("generic",
           programs.tiny_trainable(),
           ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8}}),
           programs.tiny_batch())

    cfg = TransformerConfig(vocab_size=ZOO_VOCAB, hidden_size=16,
                            num_layers=2, num_heads=2, mlp_dim=32,
                            max_len=8, dtype=jnp.float32,
                            dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    r = np.random.RandomState(0)
    lm_batch = {
        "x": r.randint(0, ZOO_VOCAB, (8, 8)).astype(np.int32),
        "y": r.randint(0, ZOO_VOCAB, (8, 8)).astype(np.int32)}
    yield ("pipeline_lm",
           make_pipeline_lm_trainable(cfg, optax.sgd(0.05),
                                      jax.random.PRNGKey(0)),
           ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8},
                         "mesh": {"data": 2, "pipe": 2, "model": 2}}),
           lm_batch)


def iter_zoo_strategies():
    """Build every :func:`default_candidates` builder against every
    fixture it fits; yields ``(name, strategy, spec, trainable, batch)``
    — byte-identical strategies deduped like AutoStrategy's own loop."""
    from autodist_tpu.simulator.auto_strategy import default_candidates

    seen_content = set()
    for fixture, trainable, spec, batch in _zoo_fixtures():
        seen_names: dict[str, int] = {}
        for builder in default_candidates():
            name = type(builder).__name__
            seen_names[name] = seen_names.get(name, 0) + 1
            if seen_names[name] > 1:
                name = f"{name}#{seen_names[name]}"
            try:
                strategy = builder.build(trainable, spec)
            except ValueError:
                continue   # candidate does not fit this fixture
            # A stage-structured trainable lowers through the pipeline
            # backend only (AutoStrategy scores the others but they
            # cannot lower it); the generic trainable exercises the
            # collective/gspmd families.
            is_pipeline = strategy.graph_config.lowering == "pipeline"
            if is_pipeline != (fixture == "pipeline_lm"):
                continue
            content = json.dumps(
                [n.to_dict() for n in strategy.node_configs]
                + [strategy.graph_config.to_dict()], sort_keys=True)
            if content in seen_content:
                continue
            seen_content.add(content)
            yield f"{fixture}/{name}", strategy, spec, trainable, batch


def _train_program_text(strategy, spec, trainable, batch) -> str:
    """Lower + compile one zoo candidate's train step on the CPU mesh."""
    import jax

    from autodist_tpu.analysis.facts import compiled_text
    from autodist_tpu.autodist import AutoDist

    runner = AutoDist(spec, "AllReduce").build(trainable, strategy)
    try:
        return compiled_text(runner.lowered.step_fn, runner.state,
                             runner._place_batch(batch),
                             jax.random.PRNGKey(0))
    finally:
        runner.close()


def lint_zoo(max_programs=None, plan_only=False, decode=True,
             reshard=True, kernel=True, paged=True,
             out=print) -> tuple[int, int, list]:
    """Sweep the zoo; returns ``(n_errors, n_warnings, results)``."""
    from autodist_tpu.analysis import (lint_plan, lint_program,
                                       rules_for_decode,
                                       rules_for_strategy)
    from autodist_tpu.analysis import programs

    results = []
    n_err = n_warn = 0
    compiled = 0
    candidates = list(iter_zoo_strategies())
    for name, strategy, spec, trainable, batch in candidates:
        rec = {"candidate": name, "lowering":
               strategy.graph_config.lowering}
        plan = lint_plan(strategy, resource_spec=spec,
                         trainable=trainable)
        rec["plan"] = [d.to_dict() for d in plan]
        n_err += len(plan.errors)
        n_warn += len(plan.warnings)
        if not plan_only:
            if max_programs is not None and compiled >= max_programs:
                rec["program"] = "skipped (--max-programs budget)"
                out(f"{name}: program lint SKIPPED "
                    "(--max-programs budget)")
            else:
                compiled += 1
                try:
                    text = _train_program_text(strategy, spec,
                                               trainable, batch)
                except Exception as e:   # a candidate that cannot lower
                    n_err += 1
                    rec["program_error"] = f"{type(e).__name__}: {e}"
                    out(f"{name}: FAILED to lower/compile — {e}")
                    results.append(rec)
                    continue
                vocab = ZOO_VOCAB if "pipeline_lm" in name else None
                rules = rules_for_strategy(strategy, vocab_size=vocab)
                prog = lint_program(text, rules, where=name)
                rec["program"] = [d.to_dict() for d in prog]
                rec["program_rules"] = [r.name for r in rules]
                n_err += len(prog.errors)
                n_warn += len(prog.warnings)
        status = []
        if plan.errors or rec.get("program_error"):
            status.append("ERRORS")
        out(f"{name}: plan {len(plan.errors)}E/{len(plan.warnings)}W"
            + ("" if plan_only or "program" not in rec
               or isinstance(rec.get("program"), str)
               else f", program {len([d for d in rec['program'] if d['severity'] == 'error'])}E"
                    f" ({len(rec.get('program_rules', []))} rules)")
            + (" " + " ".join(status) if status else ""))
        results.append(rec)

    if decode and not plan_only:
        decode_cases = [(1, False, "dense"), (2, False, "dense"),
                        (2, True, "dense")]
        if paged:
            # The paged-KV decode configs sweep through the ADT115
            # paged-cache rule (plus the shared decode contract);
            # --no-paged opts out, and the --max-programs budget guard
            # skips LOUDLY like every other program here.
            decode_cases += [(1, False, "paged"), (2, True, "paged")]
        for tp, vocab_parallel, layout in decode_cases:
            name = f"decode/tp{tp}" + ("+vocab" if vocab_parallel else "") \
                + ("+paged" if layout == "paged" else "")
            if max_programs is not None and compiled >= max_programs:
                out(f"{name}: SKIPPED (--max-programs budget)")
                results.append({"candidate": name,
                                "program": "skipped (--max-programs "
                                           "budget)"})
                continue
            compiled += 1
            text = programs.decode_step_text(tp, vocab_parallel,
                                             kv_layout=layout)
            rules = rules_for_decode(
                tp, vocab_parallel, vocab_size=programs.DEC_V,
                max_len=programs.DEC_T,
                num_layers=programs.DEC_LAYERS,
                num_slots=programs.DEC_SLOTS,
                heads_local=max(2 // tp, 1),
                head_dim=programs.DEC_HEAD_DIM,
                kv_layout=layout,
                pool_blocks=programs.DEC_POOL_BLOCKS)
            prog = lint_program(text, rules, where=name)
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
            out(f"{name}: program {len(prog.errors)}E/"
                f"{len(prog.warnings)}W ({len(rules)} rules)")
            results.append({"candidate": name,
                            "program": [d.to_dict() for d in prog],
                            "program_rules": [r.name for r in rules]})

    if reshard and not plan_only:
        # The elastic reshard program: FSDP axis-0 shards re-laid as
        # axis-1 shards, ONE compiled program — its contract (ADT110:
        # no gather beyond the target-shard budget; ADT101: no host
        # staging) is the memory-efficient-redistribution claim.
        from autodist_tpu.analysis import rules_for_reshard

        name = "reshard/axis0->axis1"
        if max_programs is not None and compiled >= max_programs:
            out(f"{name}: SKIPPED (--max-programs budget)")
            results.append({"candidate": name,
                            "program": "skipped (--max-programs "
                                       "budget)"})
        else:
            compiled += 1
            text = programs.reshard_step_text()
            rules = rules_for_reshard(programs.reshard_budget())
            prog = lint_program(text, rules, where=name)
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
            out(f"{name}: program {len(prog.errors)}E/"
                f"{len(prog.warnings)}W (gather budget "
                f"{programs.reshard_budget()} elems)")
            results.append({"candidate": name,
                            "program": [d.to_dict() for d in prog],
                            "program_rules": [r.name for r in rules]})

    if kernel and not plan_only:
        # The Pallas kernel tier: every kernel-elected program (plan
        # lint + lower/compile + the ADT120 fused_kernel_replaced proof
        # that the elected kernel actually replaced the composed ops).
        from autodist_tpu.analysis import rules_for_strategy as _rfs
        from autodist_tpu.strategy.parallel_builders import Pipeline

        kernel_cases = [
            ("kernel/quant_ring",
             dict(tensor_parallel=2,
                  collective_precision={"tp_psum": "int8"},
                  kernel=("quant_ring",)),
             dict(collective_precision=(("tp_psum", "int8"),),
                  kernel=("quant_ring",))),
            ("kernel/collective_matmul",
             dict(tensor_parallel=2, comm_overlap="matmul",
                  kernel=("collective_matmul",)),
             dict(comm_overlap="matmul",
                  kernel=("collective_matmul",))),
        ]
        fixtures = {f[0]: f for f in _zoo_fixtures()}
        _, lm_trainable, lm_spec, lm_batch = fixtures["pipeline_lm"]
        for name, bkw, pkw in kernel_cases:
            if max_programs is not None and compiled >= max_programs:
                out(f"{name}: SKIPPED (--max-programs budget)")
                results.append({"candidate": name,
                                "program": "skipped (--max-programs "
                                           "budget)"})
                continue
            compiled += 1
            strategy = Pipeline(num_microbatches=2, **bkw).build(
                lm_trainable, lm_spec)
            plan = lint_plan(strategy, resource_spec=lm_spec,
                             trainable=lm_trainable)
            n_err += len(plan.errors)
            n_warn += len(plan.warnings)
            # Default (vocab-32) geometry: shares the compile cache
            # with the mutation matrix's kernel-elected programs —
            # these plans are not vocab-parallel, so no rule needs the
            # distinctive vocab extent.
            text = programs.pipeline_step_text(2, **pkw)
            rules = _rfs(strategy)
            prog = lint_program(text, rules, where=name)
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
            out(f"{name}: plan {len(plan.errors)}E/"
                f"{len(plan.warnings)}W, program {len(prog.errors)}E"
                f" ({len(rules)} rules)")
            results.append({"candidate": name,
                            "plan": [d.to_dict() for d in plan],
                            "program": [d.to_dict() for d in prog],
                            "program_rules": [r.name for r in rules]})
        # MoE expert-parallel candidates: the composed wire ladder
        # (fp32, int8 moe_a2a) plus the a2a_ring-elected program whose
        # ADT120 proof is the fused s8 dispatch/combine ring replacing
        # the monolithic all-to-alls.  Shares the memoized moe corpus
        # with the mutation matrix.
        import jax as _jax
        import jax.numpy as _jnp
        import optax as _optax

        from autodist_tpu.models.moe_transformer import (
            MoeConfig, make_moe_lm_trainable)
        from autodist_tpu.resource import ResourceSpec
        from autodist_tpu.strategy.parallel_builders import ExpertParallel

        moe_spec = ResourceSpec({"topology": {"platform": "cpu",
                                              "num_devices": 4},
                                 "mesh": {"data": 2, "expert": 2}})
        moe_cfg = MoeConfig(vocab_size=32, hidden_size=16, num_layers=1,
                            num_heads=2, expert_hidden=32,
                            num_experts=4, max_len=8,
                            dtype=_jnp.float32)
        moe_trainable = make_moe_lm_trainable(
            moe_cfg, _optax.sgd(0.05), _jax.random.PRNGKey(0),
            batch_size=4, seq_len=8)
        moe_cases = [
            ("moe/fp32", dict(), (None, None)),
            ("moe/int8",
             dict(collective_precision={"moe_a2a": "int8"}),
             ((("moe_a2a", "int8"),), None)),
            ("moe/int8+a2a_ring",
             dict(collective_precision={"moe_a2a": "int8"},
                  kernel=("a2a_ring",)),
             ((("moe_a2a", "int8"),), ("a2a_ring",))),
        ]
        for name, bkw, (prec_key, kern_key) in moe_cases:
            if max_programs is not None and compiled >= max_programs:
                out(f"{name}: SKIPPED (--max-programs budget)")
                results.append({"candidate": name,
                                "program": "skipped (--max-programs "
                                           "budget)"})
                continue
            compiled += 1
            strategy = ExpertParallel(num_experts=4, **bkw).build(
                moe_trainable, moe_spec)
            plan = lint_plan(strategy, resource_spec=moe_spec,
                             trainable=moe_trainable)
            n_err += len(plan.errors)
            n_warn += len(plan.warnings)
            try:
                text = programs.moe_step_text(2, prec_key, kern_key)
            except Exception as e:
                n_err += 1
                out(f"{name}: FAILED to lower/compile — {e}")
                results.append({"candidate": name,
                                "plan": [d.to_dict() for d in plan],
                                "program_error":
                                    f"{type(e).__name__}: {e}"})
                continue
            rules = _rfs(strategy)
            prog = lint_program(text, rules, where=name)
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
            out(f"{name}: plan {len(plan.errors)}E/"
                f"{len(plan.warnings)}W, program {len(prog.errors)}E"
                f" ({len(rules)} rules)")
            results.append({"candidate": name,
                            "plan": [d.to_dict() for d in plan],
                            "program": [d.to_dict() for d in prog],
                            "program_rules": [r.name for r in rules]})

        flash_cases = [("kernel/flash_decode", "dense")]
        if paged:
            # The paged-cache flash decode: ADT120's marker proof plus
            # the ADT115 dense-lane ban (the in-kernel page walk leaves
            # no HLO gather, so the rule's gather half stays off).
            flash_cases.append(("kernel/flash_decode_paged", "paged"))
        for name, layout in flash_cases:
            if max_programs is not None and compiled >= max_programs:
                out(f"{name}: SKIPPED (--max-programs budget)")
                results.append({"candidate": name,
                                "program": "skipped (--max-programs "
                                           "budget)"})
                continue
            compiled += 1
            text = programs.decode_step_text(1, False,
                                             kernel=("flash_decode",),
                                             kv_layout=layout)
            rules = rules_for_decode(
                1, False, vocab_size=programs.DEC_V,
                max_len=programs.DEC_T,
                num_layers=programs.DEC_LAYERS,
                num_slots=programs.DEC_SLOTS, heads_local=2,
                head_dim=programs.DEC_HEAD_DIM,
                kernel=("flash_decode",), kv_layout=layout,
                pool_blocks=programs.DEC_POOL_BLOCKS)
            prog = lint_program(text, rules, where=name)
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
            out(f"{name}: program {len(prog.errors)}E/"
                f"{len(prog.warnings)}W ({len(rules)} rules)")
            results.append({"candidate": name,
                            "program": [d.to_dict() for d in prog],
                            "program_rules": [r.name for r in rules]})
    return n_err, n_warn, results


def _search_fixtures():
    """Topologies the searched-frontier sweep covers: the zoo fixtures'
    own topologies plus a two-slice variant of the pipeline LM (dcn
    axis derived from ``num_slices``), so CI gates the hierarchical
    (DCN) pricing path too."""
    from autodist_tpu.resource import ResourceSpec

    for name, trainable, spec, batch in _zoo_fixtures():
        yield name, trainable, spec, batch
        if name == "pipeline_lm":
            yield ("pipeline_lm@2slice", trainable,
                   ResourceSpec({"topology": {"platform": "cpu",
                                              "num_devices": 8,
                                              "num_slices": 2}}),
                   batch)

    # MoE on a two-slice topology: the search synthesizes the expert
    # family (dense point, within-slice and across-DCN placements, the
    # moe_a2a wire ladder, the a2a_ring kernel election) — this fixture
    # gates that none of it is unlintable and the hierarchical a2a
    # pricing elects a winner.
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)

    # num_heads=4: the tp knob family sweeps divisors of the 4-way ICI
    # degree, and the head axis must divide every swept tp.
    moe_cfg = MoeConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_heads=4, expert_hidden=32, num_experts=8,
                        max_len=8, dtype=jnp.float32)
    moe_trainable = make_moe_lm_trainable(moe_cfg, optax.sgd(0.05),
                                          jax.random.PRNGKey(0),
                                          batch_size=4, seq_len=8)
    r = np.random.RandomState(0)
    x = r.randint(0, 32, (8, 8)).astype(np.int32)
    yield ("moe_lm@2slice", moe_trainable,
           ResourceSpec({"topology": {"platform": "cpu",
                                      "num_devices": 8,
                                      "num_slices": 2}}),
           {"x": x, "y": np.roll(x, -1, axis=1)})


def lint_search(plan_only=False, out=print, top=10) -> tuple[int, int,
                                                             list]:
    """Sweep the searched frontier per fixture topology the way
    ``--zoo`` sweeps the fixed candidate list: the search plan-lints
    every synthesized candidate internally (lint ERROR ⇒ pruned and
    counted), so any lint-pruned candidate here is a *synthesis* bug
    and fails the sweep; every priced survivor is re-linted (belt and
    braces), and the elected winner's compiled program goes through
    the program linter.  Returns ``(n_errors, n_warnings, results)``.
    """
    import numpy as np

    from autodist_tpu.analysis import lint_plan
    from autodist_tpu.simulator.search import (program_lint_winner,
                                               search_strategies)

    results = []
    n_err = n_warn = 0
    for name, trainable, spec, batch in _search_fixtures():
        leaves = list(batch.values())
        global_batch = int(np.shape(leaves[0])[0])
        res = search_strategies(trainable, spec,
                                global_batch=global_batch)
        rec = {"fixture": name, "counts": res.counts(),
               "lint_pruned": [{"candidate": cand, "codes": codes}
                               for cand, codes in res.lint_pruned]}
        # The search must never synthesize an unlintable plan from a
        # valid knob point: every lint prune is a bug, not input error.
        n_err += len(res.lint_pruned)
        surv_err = 0
        for cand in res.frontier:
            rep = lint_plan(cand.strategy, resource_spec=cand.spec,
                            trainable=trainable)
            surv_err += len(rep.errors)
            n_warn += len(rep.warnings)
        n_err += surv_err
        rec["survivor_errors"] = surv_err
        rec["frontier"] = [
            {"candidate": c.name, "feasible": c.cost.feasible,
             "comm_time_s": c.cost.comm_time_s,
             "dcn_time_s": c.cost.dcn_time_s}
            for c in res.frontier[:top]]
        winner = res.winner.name if res.winner else None
        rec["winner"] = winner
        if not plan_only and res.winner is not None:
            vocab = ZOO_VOCAB if name.startswith("pipeline_lm") else None
            try:
                prog = program_lint_winner(res, trainable, batch,
                                           vocab_size=vocab)
            except Exception as e:   # a winner that cannot lower
                n_err += 1
                rec["winner_program_error"] = f"{type(e).__name__}: {e}"
                out(f"{name}: winner {winner} FAILED to "
                    f"lower/compile — {e}")
                results.append(rec)
                continue
            rec["winner_program"] = [d.to_dict() for d in prog]
            n_err += len(prog.errors)
            n_warn += len(prog.warnings)
        out(f"{name}: {res.raw_configs} raw, "
            f"{res.skipped_unbuildable} unbuildable, "
            f"{res.pruned_dominated} dominated, "
            f"{res.pruned_lint} lint-pruned, {res.priced} priced; "
            f"winner {winner}"
            + ("" if plan_only or "winner_program" not in rec
               else f", program {len([d for d in rec['winner_program'] if d['severity'] == 'error'])}E"))
        results.append(rec)
    return n_err, n_warn, results


def run_mutation_matrix(out=print) -> tuple[int, list]:
    from autodist_tpu.analysis.mutations import run_mutations

    results = run_mutations()
    failed = 0
    for rec in results:
        if rec["ok"]:
            out(f"mutation {rec['name']:<38} {rec['code']} fired")
        else:
            failed += 1
            out(f"mutation {rec['name']:<38} {rec['code']} FAILED "
                f"(clean_ok={rec['clean_ok']}, fired={rec['fired']})")
    out(f"mutation matrix: {len(results) - failed}/{len(results)} "
        "rules fire on their seeded violations")
    return failed, results


def lint_files(paths, out=print) -> tuple[int, list]:
    """Plan-lint serialized strategy JSON files."""
    from autodist_tpu.analysis import lint_plan
    from autodist_tpu.strategy.ir import Strategy

    n_err = 0
    results = []
    for path in paths:
        with open(path) as f:
            strategy = Strategy.from_json(f.read())
        report = lint_plan(strategy)
        n_err += len(report.errors)
        out(report.render(title=path))
        results.append({"path": path,
                        "plan": [d.to_dict() for d in report]})
    return n_err, results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Plan + program lint over the AutoStrategy zoo "
                    "(ADT diagnostics; rc 1 on any ERROR)")
    ap.add_argument("strategies", nargs="*",
                    help="serialized strategy JSON files to plan-lint")
    ap.add_argument("--zoo", action="store_true",
                    help="sweep every AutoStrategy candidate (plan "
                         "lint + program lint) and the decode configs")
    ap.add_argument("--search", action="store_true",
                    help="sweep the topology-aware searched frontier "
                         "per fixture topology (plan lint on every "
                         "survivor, program lint on the winner) — the "
                         "--zoo analog for synthesized candidates")
    ap.add_argument("--mutate", action="store_true",
                    help="run the mutation-test harness (each rule "
                         "must fire on its seeded violation)")
    ap.add_argument("--plan-only", action="store_true",
                    help="skip the program compiles (plan lint only)")
    ap.add_argument("--no-decode", action="store_true",
                    help="skip the decode-window programs")
    ap.add_argument("--no-reshard", action="store_true",
                    help="skip the elastic reshard program")
    ap.add_argument("--no-kernel", action="store_true",
                    help="skip the Pallas kernel-elected programs")
    ap.add_argument("--no-paged", action="store_true",
                    help="skip the paged-KV decode programs")
    ap.add_argument("--max-programs", type=int, default=None,
                    metavar="N",
                    help="compile at most N programs (CI budget "
                         "guard); skipped programs are listed")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable report here")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: compact output, same rc contract "
                         "(rc 1 on any ERROR / non-firing mutation)")
    args = ap.parse_args(argv)
    if not (args.zoo or args.search or args.mutate or args.strategies):
        ap.error("nothing to do: pass --zoo, --search, --mutate, "
                 "and/or strategy JSON files")

    out = (lambda *a, **k: None) if args.check else print
    n_err = 0
    report = {}
    if args.strategies:
        file_err, report["files"] = lint_files(args.strategies, out=out)
        n_err += file_err
    if args.zoo:
        zoo_err, zoo_warn, report["zoo"] = lint_zoo(
            max_programs=args.max_programs, plan_only=args.plan_only,
            decode=not args.no_decode, reshard=not args.no_reshard,
            kernel=not args.no_kernel, paged=not args.no_paged,
            out=out)
        n_err += zoo_err
        print(f"zoo sweep: {zoo_err} error(s), {zoo_warn} warning(s) "
              f"across {len(report['zoo'])} candidate(s)")
    if args.search:
        s_err, s_warn, report["search"] = lint_search(
            plan_only=args.plan_only, out=out)
        n_err += s_err
        print(f"search sweep: {s_err} error(s), {s_warn} warning(s) "
              f"across {len(report['search'])} fixture(s)")
    if args.mutate:
        mut_failed, report["mutations"] = run_mutation_matrix(out=out)
        n_err += mut_failed
        if mut_failed:
            print(f"mutation matrix: {mut_failed} rule(s) did NOT fire")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
