#!/usr/bin/env python
"""Trace-driven load generation for the serving fleet.

Serving papers agree on one thing about traffic: it is never constant.
Production request streams breathe on a daily cycle (diurnal), spike in
correlated bursts (a client retry storm, a page going viral), and carry
heavy-tailed inter-arrival gaps (a Poisson assumption undershoots the
p99 queue depth badly).  An autoscaler tuned against a constant-rate
generator learns nothing about any of those — so this module generates
the three canonical shapes, seeded and reproducible, as explicit
arrival traces the autoscaler tests replay:

* :func:`diurnal_trace` — an inhomogeneous Poisson process whose rate
  rides a sinusoid between ``base_rps`` and ``peak_rps`` (thinning
  construction: draw at the peak rate, keep with probability
  ``rate(t)/peak``).
* :func:`bursty_trace` — an on/off (interrupted Poisson) process:
  quiet ``idle_rps`` stretches punctuated by ``burst_s``-long windows
  at ``burst_rps``.
* :func:`heavy_tail_trace` — Pareto inter-arrival gaps (index
  ``alpha``), scaled so the MEAN rate is still ``rps`` — same average
  load as Poisson, far lumpier arrivals.

A trace is a list of :class:`Arrival` rows (arrival time, prompt,
decode budget), so it can be saved, inspected, or replayed against any
``submit``-shaped callable.  :class:`LoadReplay` is the incremental
consumer the serving loop polls (``due(now)`` → the arrivals whose time
has come); :func:`replay` is the batteries-included real-time driver.

CLI: ``python tools/loadgen.py --trace bursty --duration 5 --seed 0``
prints the trace as JSON lines plus a rate summary.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One request's arrival: when, what prompt, how many tokens."""

    t_s: float
    prompt: tuple
    max_new_tokens: int

    def to_dict(self) -> dict:
        return {"t_s": self.t_s, "prompt": list(self.prompt),
                "max_new_tokens": self.max_new_tokens}


def _requests(rng: np.random.RandomState, times, *, vocab_size: int,
              prompt_len, max_new_tokens) -> list:
    """Attach a random prompt + budget to each arrival time (uniform
    over the given ``(lo, hi)`` inclusive ranges, ids in
    ``[1, vocab_size)`` — 0 is the conventional pad)."""
    p_lo, p_hi = prompt_len
    m_lo, m_hi = max_new_tokens
    out = []
    for t in times:
        n = int(rng.randint(p_lo, p_hi + 1))
        prompt = tuple(int(x) for x in rng.randint(1, vocab_size, n))
        out.append(Arrival(t_s=float(t), prompt=prompt,
                           max_new_tokens=int(rng.randint(m_lo,
                                                          m_hi + 1))))
    return out


def diurnal_trace(*, duration_s: float, base_rps: float, peak_rps: float,
                  period_s: Optional[float] = None, seed: int = 0,
                  vocab_size: int = 32, prompt_len=(2, 6),
                  max_new_tokens=(4, 8)) -> list:
    """The daily-cycle shape: rate rides a sinusoid from ``base_rps``
    (trough, at t=0) up to ``peak_rps`` and back over ``period_s``
    (default: one full cycle across the duration)."""
    if peak_rps < base_rps:
        raise ValueError("peak_rps must be >= base_rps")
    period = float(period_s or duration_s)
    rng = np.random.RandomState(seed)
    times, t = [], 0.0
    while True:
        t += float(rng.exponential(1.0 / max(peak_rps, 1e-9)))
        if t >= duration_s:
            break
        phase = 0.5 - 0.5 * np.cos(2 * np.pi * t / period)
        rate = base_rps + (peak_rps - base_rps) * phase
        if rng.uniform() < rate / peak_rps:    # thinning
            times.append(t)
    return _requests(rng, times, vocab_size=vocab_size,
                     prompt_len=prompt_len, max_new_tokens=max_new_tokens)


def bursty_trace(*, duration_s: float, idle_rps: float, burst_rps: float,
                 burst_s: float, gap_s: float, seed: int = 0,
                 vocab_size: int = 32, prompt_len=(2, 6),
                 max_new_tokens=(4, 8)) -> list:
    """The on/off shape: ``gap_s`` of ``idle_rps`` background, then
    ``burst_s`` at ``burst_rps``, repeating.  The first burst starts at
    ``gap_s`` — a replayed trace begins calm, so a test observes the
    autoscaler's grow edge AND the shrink after the burst drains."""
    if burst_rps < idle_rps:
        raise ValueError("burst_rps must be >= idle_rps")
    rng = np.random.RandomState(seed)
    times, t = [], 0.0
    cycle = gap_s + burst_s
    # Thinning against the burst rate: stepping at the CURRENT regime's
    # rate would let one long idle gap leap clean over a whole burst.
    while True:
        t += float(rng.exponential(1.0 / max(burst_rps, 1e-9)))
        if t >= duration_s:
            break
        in_burst = (t % cycle) >= gap_s
        rate = burst_rps if in_burst else idle_rps
        if rng.uniform() < rate / burst_rps:
            times.append(t)
    return _requests(rng, times, vocab_size=vocab_size,
                     prompt_len=prompt_len, max_new_tokens=max_new_tokens)


def heavy_tail_trace(*, duration_s: float, rps: float, alpha: float = 1.5,
                     seed: int = 0, vocab_size: int = 32,
                     prompt_len=(2, 6), max_new_tokens=(4, 8)) -> list:
    """The heavy-tailed shape: Pareto(``alpha``) inter-arrival gaps
    with the scale chosen so the mean gap is ``1/rps`` (requires
    ``alpha > 1`` for the mean to exist) — most gaps are short (packed
    arrivals), a few are very long (dead air), at the same average
    rate a Poisson process would give."""
    if alpha <= 1.0:
        raise ValueError("alpha must be > 1 (finite mean gap)")
    rng = np.random.RandomState(seed)
    mean_gap = 1.0 / max(rps, 1e-9)
    scale = mean_gap * (alpha - 1.0) / alpha
    times, t = [], 0.0
    while True:
        t += float(scale * (1.0 + rng.pareto(alpha)))
        if t >= duration_s:
            break
        times.append(t)
    return _requests(rng, times, vocab_size=vocab_size,
                     prompt_len=prompt_len, max_new_tokens=max_new_tokens)


#: name -> generator, for CLIs and tests that pick a shape by string.
TRACES: dict = {
    "diurnal": diurnal_trace,
    "bursty": bursty_trace,
    "heavy-tail": heavy_tail_trace,
}


class LoadReplay:
    """Incremental trace consumer for a polling serving loop: each
    ``due(now)`` call returns the arrivals whose time has come (in
    order, each exactly once), where ``now`` is seconds since the
    replay's epoch — the caller owns the clock, so tests can drive a
    virtual one."""

    def __init__(self, trace):
        self._trace = sorted(trace, key=lambda a: a.t_s)
        self._i = 0

    def due(self, now: float) -> list:
        start = self._i
        while self._i < len(self._trace) \
                and self._trace[self._i].t_s <= now:
            self._i += 1
        return self._trace[start:self._i]

    @property
    def exhausted(self) -> bool:
        return self._i >= len(self._trace)

    @property
    def remaining(self) -> int:
        return len(self._trace) - self._i


def replay(trace, submit: Callable, *, speed: float = 1.0,
           clock: Callable[[], float] = time.perf_counter,
           sleep: Callable[[float], None] = time.sleep) -> int:
    """Real-time replay: call ``submit(arrival)`` at each arrival's
    time (divided by ``speed`` — 10.0 replays a 10-minute trace in a
    minute).  Returns the number submitted."""
    rep = LoadReplay(trace)
    t0 = clock()
    n = 0
    while not rep.exhausted:
        now = (clock() - t0) * speed
        batch = rep.due(now)
        if not batch:
            nxt = rep._trace[rep._i].t_s
            sleep(max((nxt - now) / speed, 0.0))
            continue
        for arrival in batch:
            submit(arrival)
            n += 1
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", choices=sorted(TRACES), default="diurnal")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rps", type=float, default=8.0,
                    help="mean rate (heavy-tail) / peak rate (others)")
    args = ap.parse_args(argv)
    if args.trace == "diurnal":
        trace = diurnal_trace(duration_s=args.duration,
                              base_rps=args.rps / 4, peak_rps=args.rps,
                              seed=args.seed)
    elif args.trace == "bursty":
        trace = bursty_trace(duration_s=args.duration,
                             idle_rps=args.rps / 8, burst_rps=args.rps,
                             burst_s=args.duration / 5,
                             gap_s=args.duration / 5, seed=args.seed)
    else:
        trace = heavy_tail_trace(duration_s=args.duration, rps=args.rps,
                                 seed=args.seed)
    for a in trace:
        print(json.dumps(a.to_dict()))
    rate = len(trace) / args.duration if args.duration else 0.0
    print(f"# {len(trace)} arrivals over {args.duration:.1f}s "
          f"({rate:.2f} rps mean)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
