"""Reshard a checkpoint directory onto a new strategy/mesh, offline.

The CLI face of :mod:`autodist_tpu.elastic`: given a source checkpoint
directory (written by ``Saver.save`` — the elastic sidecar carries the
source layout) and a target strategy, produce a NEW checkpoint
directory whose state is laid out for the target, printing the
reshard plan-lint verdict (ADT070/ADT071) and — when the source mesh
can be rebuilt on this host — the ADT110 program-lint verdict of the
compiled transfer::

    # explicit target strategy JSON (e.g. a hand-edited or serialized one)
    python tools/reshard_ckpt.py CKPT_DIR OUT_DIR \
        --trainable examples.my_model:make_trainable \
        --strategy target_strategy.json

    # let the topology-aware search elect the target for N devices
    python tools/reshard_ckpt.py CKPT_DIR OUT_DIR \
        --trainable examples.my_model:make_trainable \
        --auto-search --num-devices 4

``--trainable module:function`` names a zero-arg (or
``--trainable-kwargs`` JSON-kwargs) factory returning the Trainable
the checkpoint belongs to — a checkpoint alone does not define the
model.  Exit code: 1 on any lint ERROR or failed restore.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

if __name__ == "__main__":  # simulated mesh before the first jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_trainable(spec: str, kwargs_json: str = ""):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(f"--trainable {spec!r}: expected module:function")
    sys.path.insert(0, os.getcwd())
    module = importlib.import_module(mod_name)
    factory = getattr(module, fn_name)
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    return factory(**kwargs)


def resolve_target(trainable, args):
    """The target (strategy, spec) from --strategy or --auto-search."""
    import jax

    from autodist_tpu.elastic.reshard import spec_for_layout
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy.ir import Strategy

    n = args.num_devices or jax.device_count()
    if args.strategy:
        with open(args.strategy) as f:
            strategy = Strategy.from_json(f.read())
        return strategy, spec_for_layout(
            strategy.graph_config.mesh_axes, fallback_devices=n)
    if not args.auto_search:
        raise SystemExit("pass --strategy target.json or --auto-search")
    from autodist_tpu.simulator.search import search_strategies

    spec = ResourceSpec({"topology": {"num_devices": n}})
    result = search_strategies(trainable, spec,
                               global_batch=args.global_batch)
    print(result.report(top=5))
    if result.winner is None:
        raise SystemExit("auto-search priced no candidate for "
                         f"{n} devices")
    return result.winner.strategy, result.winner.spec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source", help="source checkpoint directory")
    ap.add_argument("out", help="output (resharded) checkpoint directory")
    ap.add_argument("--trainable", required=True,
                    metavar="MODULE:FUNCTION",
                    help="factory returning the checkpoint's Trainable")
    ap.add_argument("--trainable-kwargs", default="",
                    help="JSON kwargs for the factory")
    ap.add_argument("--strategy", default=None,
                    help="target strategy JSON file")
    ap.add_argument("--auto-search", action="store_true",
                    help="elect the target via the topology-aware "
                         "search instead of --strategy")
    ap.add_argument("--num-devices", type=int, default=None,
                    help="target device count (default: all visible)")
    ap.add_argument("--global-batch", type=int, default=None,
                    help="global batch the searched target must divide")
    ap.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: latest)")
    ap.add_argument("--source-strategy", default=None,
                    help="strategy JSON the checkpoint was WRITTEN "
                         "under — required for pre-elastic checkpoints "
                         "(no sidecar), where the source layout must "
                         "be rebuilt")
    args = ap.parse_args(argv)

    from autodist_tpu.analysis import (lint_program, lint_reshard,
                                       rules_for_reshard)
    from autodist_tpu.autodist import AutoDist
    from autodist_tpu.checkpoint.saver import Saver
    from autodist_tpu.elastic.reshard import (build_convert_fn,
                                              shard_budget)

    trainable = load_trainable(args.trainable, args.trainable_kwargs)
    strategy, spec = resolve_target(trainable, args)

    saver = Saver(args.source)
    step = args.step if args.step is not None else saver.latest_step()
    if step is None:
        print(f"no checkpoints in {args.source}", file=sys.stderr)
        return 1
    sidecar = saver.read_sidecar(step)

    runner = AutoDist(spec).build(trainable, strategy)
    source_strategy = None
    if args.source_strategy:
        from autodist_tpu.strategy.ir import Strategy

        with open(args.source_strategy) as f:
            source_strategy = Strategy.from_json(f.read())

    # Plan-lint verdict BEFORE moving anything (restore_elastic would
    # also refuse, but the CLI's job is to show the full report).
    rc = 0
    if sidecar is not None:
        dst_manifest = runner.lowered.state_manifest(runner.state)
        report = lint_reshard(sidecar["manifest"], dst_manifest)
        print(report.render(title=f"reshard plan lint (step {step})"))
        if not report.ok:
            return 1
    elif source_strategy is None:
        print(f"step {step}: no elastic sidecar (pre-elastic "
              "checkpoint) — source layout-unknown; pass "
              "--source-strategy with the strategy JSON the writer "
              "ran", file=sys.stderr)
        return 1

    try:
        saver.restore_elastic(runner, step=step,
                              strategy=source_strategy)
    except (ValueError, RuntimeError) as e:
        print(f"restore failed: {e}", file=sys.stderr)
        return 1
    out = Saver(os.path.abspath(args.out))
    out.save(runner, force=True, blocking=True)
    print(f"resharded checkpoint step {step}: {args.source} "
          f"({(sidecar or {}).get('mesh_axes')}) -> {args.out} "
          f"({dict(runner.lowered.mesh.shape)})")

    # ADT110 program-lint verdict: compile the fast-path transfer when
    # the source mesh can still be built on this host.
    if sidecar is not None:
        try:
            from autodist_tpu.elastic.reshard import spec_for_layout
            from autodist_tpu.strategy.ir import Strategy

            src_strategy = (Strategy.from_json(json.dumps(
                sidecar["strategy"])) if sidecar.get("strategy") else None)
            mesh_axes = dict(sidecar.get("mesh_axes") or {})
            if src_strategy is None or not mesh_axes:
                raise ValueError("sidecar carries no source strategy")
            src_lowered = AutoDist(spec_for_layout(mesh_axes))._lower(
                trainable, src_strategy)
            src_state = src_lowered.init_state(trainable=trainable)
            convert, _ = build_convert_fn(src_lowered, src_state,
                                          runner.lowered)
            text = convert.lower(src_state).compile().as_text()
            budget = shard_budget((runner.lowered, runner.state))
            prog = lint_program(text, rules_for_reshard(budget),
                                where="reshard program")
            print(prog.render(
                title=f"reshard program lint (ADT110 gather budget "
                      f"{budget} elems)"))
            rc = 0 if prog.ok else 1
        except (ValueError, RuntimeError) as e:
            print("reshard program lint n/a (host-staged route: "
                  f"{e})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
