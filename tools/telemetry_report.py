"""Render a telemetry run directory as a markdown report.

The consumer side of :mod:`autodist_tpu.telemetry`: given the directory
a run flushed (``metrics.jsonl`` + ``manifest.json`` + ``trace.json`` +
optional ``drift.json``), print a markdown summary — step-time p50/p99,
examples/sec, MFU when recorded, counter/gauge values, and the
predicted-vs-measured drift ratios.  ``--check`` validates the artifact
schema and exits non-zero on a break, so a tier-1 smoke invocation turns
a silent schema drift into a CI failure::

    python tools/telemetry_report.py /tmp/run1
    python tools/telemetry_report.py /tmp/run1 --check
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_STEP_KEYS = {"kind", "step", "duration_ms"}
# Per-boundary precision gauges (the Strategy IR policy): the lowering
# emits `precision/<boundary>_bits` for every narrowed boundary, so a
# run whose manifest declares a collective_precision annotation but
# whose metrics lack the gauges means a lowering silently dropped the
# policy — a schema break, caught by --check in CI.
_PRECISION_BITS = {"fp32": 32, "bf16": 16, "int8": 8}
# Fused-kernel election gauges (the Strategy IR kernel slot): the
# lowering that honors an election emits `kernel/<name>_elected` = 1
# (the pipeline lowering for the training kernels, the serving engine
# for flash_decode); a manifest run.kernel annotation without its gauge
# means the election was silently dropped — --check fails it.
_KERNEL_CHOICES = ("flash_decode", "flash_prefill", "quant_ring",
                   "collective_matmul", "a2a_ring")
# Per-request serving records (autodist_tpu/serving/batcher.py): the
# latency facts the serving section aggregates.  The PR-16 throughput-
# ladder fields are REQUIRED: every completion reports its prefix hit
# blocks, speculative proposal/acceptance tallies, and how many chunked
# prefill dispatches admitted it (1 = single-shot) — a serve record
# without them means the batcher dropped the rung accounting.
_SERVE_KEYS = {"kind", "request", "tokens", "ttft_ms", "tokens_per_sec",
               "kv_layout", "prefix_hit_blocks", "spec_proposed",
               "spec_accepted", "prefill_chunks"}
# Paged-KV pool gauges (autodist_tpu/serving/engine.py): a paged
# engine emits serve/kv_blocks_free + serve/kv_blocks_used on every
# block reservation/release.  A run whose serve records declare
# kv_layout="paged" but whose metrics lack the pool gauges means the
# block accounting silently never ran — --check fails it.
_KV_BLOCK_GAUGES = ("serve/kv_blocks_free", "serve/kv_blocks_used")
# Per-reshard records (autodist_tpu/elastic/reshard.py): one per
# executed reshard — route taken (compiled fast path vs host-staged),
# payload moved, and the host-memory high-water mark the staged route
# is bounded by.
_RESHARD_KEYS = {"kind", "route", "leaves", "bytes_moved",
                 "peak_host_bytes", "duration_ms"}
# Chaos/fault records (autodist_tpu/runtime/faults.py + the supervised
# recovery paths): one per injection and one per detected outcome.  A
# run whose injections have no matching terminal record is a run that
# claims chaos coverage it never proved — --check fails it.
_FAULT_KEYS = {"kind", "fault", "target", "phase"}
_FAULT_KINDS = ("worker_crash", "worker_hang", "slow_host", "coord_drop",
                "ckpt_write_fail", "preempt_signal",
                # serving plane (fleet replicas)
                "replica_crash", "replica_hang", "replica_slow")
_FAULT_PHASES = ("injected", "detected", "recovered", "degraded",
                 "escalated", "teardown")
_FAULT_TERMINAL = ("recovered", "degraded", "escalated", "teardown")
# Fleet dispatch records (autodist_tpu/serving/router.py): one per
# routing decision.  reason names why the request moved; re_emitted is
# the at-most-once contract made auditable — the router NEVER re-emits
# an already-streamed token, so any nonzero value is a broken stream
# and --check fails it.  A failover record must pair with the replica
# fault/health record the fleet emitted when it declared the source
# replica dead — a failover with no recorded cause is a recovery path
# that cannot be audited.
_DISPATCH_KEYS = {"kind", "request", "replica", "reason", "re_emitted"}
_DISPATCH_REASONS = ("route", "failover", "hedge", "drain")
# Disaggregated-serving handoff records (autodist_tpu/serving/disagg.py):
# one per prefill→decode KV-prefix transfer.  The replica ids come
# PAIRED — a handoff names both the prefill replica that produced the
# prefix and the decode replica that adopted it, or the route cannot be
# audited; and the per-device gather must sit under the shard budget
# (the executed form of the ADT072/ADT110 contract).
_HANDOFF_KEYS = {"kind", "route", "blocks", "bytes_moved", "duration_ms",
                 "prefill_replica", "decode_replica"}
_HANDOFF_ROUTES = ("ici", "dcn")
# Autoscaler transition records (autodist_tpu/serving/autoscale.py):
# one per grow/shrink.  Each names the trigger that fired and its
# measured value vs threshold; --check additionally requires the
# trigger's gauge (autoscale/queue_depth / autoscale/ttft_p99_ms) in
# the same run — a scale event whose trigger signal was never emitted
# is a decision nobody can audit.
_SCALE_KEYS = {"kind", "direction", "trigger", "value", "threshold",
               "replicas_before", "replicas_after"}
_SCALE_TRIGGERS = {"queue_depth": "autoscale/queue_depth",
                   "ttft_p99": "autoscale/ttft_p99_ms"}
# Online drift-breach records (autodist_tpu/telemetry/drift.py
# DriftMonitor): one per threshold CROSSING (edge-triggered), naming the
# cost-model term, the measured/predicted ratio that crossed, and which
# side of the band it left — the live sibling of the post-hoc
# drift.json report.
_DRIFT_KEYS = {"kind", "term", "ratio", "threshold", "step",
               "predicted", "measured", "direction"}
_KINDS = ("step", "serve", "reshard", "fault", "dispatch", "handoff",
          "scale", "drift", "counter", "gauge", "histogram")


def _event_trace_ids(ev: dict):
    """Trace ids a chrome-trace event is tagged with (``args.trace_id``
    for a single-request span/instant, ``args.trace_ids`` for a fused
    batch span covering several requests)."""
    args = ev.get("args") or {}
    ids = []
    if args.get("trace_id"):
        ids.append(args["trace_id"])
    ids.extend(t for t in (args.get("trace_ids") or []) if t)
    return ids


def load_jsonl(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i + 1}: not JSON ({e})")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{i + 1}: not an object")
            records.append(rec)
    return records


def check_schema(run_dir: str) -> list[str]:
    """Schema violations across the run's artifacts ([] = clean)."""
    problems = []
    jsonl = os.path.join(run_dir, "metrics.jsonl")
    if not os.path.exists(jsonl):
        return [f"missing {jsonl}"]
    try:
        records = load_jsonl(jsonl)
    except ValueError as e:
        return [str(e)]
    for i, rec in enumerate(records):
        kind = rec.get("kind")
        if kind not in _KINDS:
            problems.append(f"metrics.jsonl:{i + 1}: unknown kind {kind!r}")
        elif kind == "step":
            missing = _STEP_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: step record missing "
                    f"{sorted(missing)}")
        elif kind == "serve":
            missing = _SERVE_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: serve record missing "
                    f"{sorted(missing)}")
            else:
                if rec["spec_accepted"] > rec["spec_proposed"]:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: spec_accepted="
                        f"{rec['spec_accepted']} exceeds spec_proposed="
                        f"{rec['spec_proposed']} — the verify pass "
                        "accepted tokens the draft never proposed")
                if rec["prefill_chunks"] < 1:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: prefill_chunks="
                        f"{rec['prefill_chunks']!r} — an admitted "
                        "request spans at least one prefill dispatch")
        elif kind == "reshard":
            missing = _RESHARD_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: reshard record missing "
                    f"{sorted(missing)}")
            elif rec["route"] == "compiled" \
                    and rec.get("peak_host_bytes"):
                problems.append(
                    f"metrics.jsonl:{i + 1}: compiled-route reshard "
                    f"claims peak_host_bytes="
                    f"{rec['peak_host_bytes']} — the fast path must "
                    "never stage through the host")
        elif kind == "fault":
            missing = _FAULT_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: fault record missing "
                    f"{sorted(missing)}")
            else:
                if rec["fault"] not in _FAULT_KINDS:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown fault kind "
                        f"{rec['fault']!r}")
                if rec["phase"] not in _FAULT_PHASES:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown fault phase "
                        f"{rec['phase']!r}")
        elif kind == "dispatch":
            missing = _DISPATCH_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: dispatch record missing "
                    f"{sorted(missing)}")
            else:
                if rec["reason"] not in _DISPATCH_REASONS:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown dispatch "
                        f"reason {rec['reason']!r} (have "
                        f"{list(_DISPATCH_REASONS)})")
                if rec["re_emitted"] != 0:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: dispatch re_emitted="
                        f"{rec['re_emitted']!r} — the at-most-once "
                        "contract re-emitted tokens to a client "
                        "stream")
        elif kind == "handoff":
            missing = _HANDOFF_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: handoff record missing "
                    f"{sorted(missing)}")
            else:
                if rec["route"] not in _HANDOFF_ROUTES:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown handoff route "
                        f"{rec['route']!r} (have {list(_HANDOFF_ROUTES)})")
                if not rec["prefill_replica"] or not rec["decode_replica"]:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: handoff without its "
                        "paired prefill/decode replica ids — the "
                        "transfer route cannot be audited")
                gather = rec.get("per_device_gather_elems")
                budget = rec.get("budget_elems")
                if gather is not None and budget and gather > budget:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: handoff per-device "
                        f"gather {gather} exceeds its shard budget "
                        f"{budget} — a full-pool staging the ADT072 "
                        "contract forbids")
        elif kind == "scale":
            missing = _SCALE_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: scale record missing "
                    f"{sorted(missing)}")
            else:
                if rec["direction"] not in ("grow", "shrink"):
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown scale "
                        f"direction {rec['direction']!r}")
                if rec["trigger"] not in _SCALE_TRIGGERS:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown scale trigger "
                        f"{rec['trigger']!r} (have "
                        f"{sorted(_SCALE_TRIGGERS)})")
                delta = rec["replicas_after"] - rec["replicas_before"]
                want = 1 if rec["direction"] == "grow" else -1
                if delta != want:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: {rec['direction']} "
                        f"claims {rec['replicas_before']} -> "
                        f"{rec['replicas_after']} replicas — a scale "
                        "step moves the count by exactly one")
        elif kind == "drift":
            missing = _DRIFT_KEYS - set(rec)
            if missing:
                problems.append(
                    f"metrics.jsonl:{i + 1}: drift record missing "
                    f"{sorted(missing)}")
            else:
                if rec["direction"] not in ("over", "under"):
                    problems.append(
                        f"metrics.jsonl:{i + 1}: unknown drift "
                        f"direction {rec['direction']!r}")
                elif abs(rec["ratio"] - 1.0) <= rec["threshold"]:
                    problems.append(
                        f"metrics.jsonl:{i + 1}: drift record for "
                        f"{rec['term']!r} with ratio {rec['ratio']} "
                        f"INSIDE its ±{rec['threshold']} band — a "
                        "breach record that never breached")
        elif "name" not in rec:
            problems.append(f"metrics.jsonl:{i + 1}: {kind} without name")
        elif kind == "histogram" and "count" not in rec:
            problems.append(f"metrics.jsonl:{i + 1}: histogram without count")

    # Every injected fault must reach a terminal outcome record
    # (recovered / degraded / escalated / teardown) for the same fault
    # kind and target — an injection with no outcome means the recovery
    # path silently never ran (or never recorded), which is exactly the
    # regression the chaos harness exists to catch.
    faults = [r for r in records if r.get("kind") == "fault"
              and _FAULT_KEYS <= set(r)]
    for rec in faults:
        if rec["phase"] != "injected":
            continue
        matched = any(
            o is not rec and o["fault"] == rec["fault"]
            and o["phase"] in _FAULT_TERMINAL
            and o["target"] == rec["target"]
            for o in faults)
        if not matched:
            problems.append(
                f"metrics.jsonl: injected fault "
                f"{rec['fault']}@{rec['target']} has no matching "
                f"recovery/degrade/escalation/teardown record — the "
                "recovery path never ran or never recorded")

    # A failover dispatch must pair with the fault/health record the
    # fleet emitted for the replica it failed AWAY from: a failover
    # with no recorded cause is a recovery nobody can audit (and a
    # telltale of a router re-homing healthy replicas' work).
    dispatches = [r for r in records if r.get("kind") == "dispatch"
                  and _DISPATCH_KEYS <= set(r)]
    fault_targets = {r.get("target") for r in faults}
    for rec in dispatches:
        if rec["reason"] != "failover":
            continue
        src = rec.get("from_replica")
        if src is None or src not in fault_targets:
            problems.append(
                f"metrics.jsonl: failover dispatch for "
                f"{rec.get('request')} names from_replica={src!r} with "
                "no paired fault/health record for that replica — an "
                "unaudited failover")
            continue
        # The PR-19 causal-chain gate, keyed on the distributed trace
        # id (absent on pre-tracing runs, which keep passing on the
        # pairing gate above alone): the failed-over trace must show a
        # PRIOR dispatch onto the replica it claims to flee — a
        # failover whose own trace never touched that replica is a
        # router re-homing work it never lost.
        tid = rec.get("trace_id")
        if tid is not None:
            on_src = any(o is not rec and o.get("trace_id") == tid
                         and o.get("replica") == src
                         for o in dispatches)
            if not on_src:
                problems.append(
                    f"metrics.jsonl: failover dispatch for trace "
                    f"{tid} claims from_replica={src!r} but the trace "
                    "has no dispatch record onto that replica — the "
                    "causal chain (dispatch → fault → failover) is "
                    "broken")

    trace = os.path.join(run_dir, "trace.json")
    trace_events: list = []
    trace_ok = False
    if os.path.exists(trace):
        try:
            with open(trace) as f:
                data = json.load(f)
            events = data["traceEvents"]
            for j, ev in enumerate(events):
                if not {"name", "ph", "ts"} <= set(ev):
                    problems.append(f"trace.json: event {j} malformed")
                    break
            else:
                trace_events = events
                trace_ok = True
        except (ValueError, KeyError, TypeError) as e:
            problems.append(f"trace.json: invalid chrome trace ({e})")

    # The PR-19 handoff causal gate, keyed the same trace-id way: a
    # ``kind="handoff"`` record tagged with a trace id claims "this
    # request prefilled on one pool and decoded on another" — the
    # stitched trace must actually contain BOTH halves (a prefill span
    # and a decode span tagged with the same id), or the KV transfer
    # moved a prefix no traced prefill produced / no traced decode
    # consumed.  Untagged (pre-tracing) handoffs keep passing.
    if trace_ok:
        tagged = {}
        for ev in trace_events:
            name = str(ev.get("name", ""))
            for t in _event_trace_ids(ev):
                got = tagged.setdefault(t, set())
                if "prefill" in name:
                    got.add("prefill")
                if "decode" in name:
                    got.add("decode")
        for rec in records:
            if rec.get("kind") != "handoff":
                continue
            tid = rec.get("trace_id")
            if tid is None:
                continue
            got = tagged.get(tid, set())
            missing = {"prefill", "decode"} - got
            if missing:
                problems.append(
                    f"metrics.jsonl: handoff record for trace {tid} "
                    f"has no {'/'.join(sorted(missing))} span tagged "
                    "with that trace id in trace.json — a KV transfer "
                    "outside its request's causal chain")

    # Any precision gauge must carry a legal wire width.
    gauges = {r.get("name"): r for r in records if r.get("kind") == "gauge"}
    for name, rec in gauges.items():
        if isinstance(name, str) and name.startswith("precision/") \
                and name.endswith("_bits") \
                and rec.get("value") not in _PRECISION_BITS.values():
            problems.append(
                f"metrics.jsonl: {name} = {rec.get('value')!r} is not a "
                f"wire width in {sorted(_PRECISION_BITS.values())}")
        # Fused-kernel election gauges: the name must be a registered
        # kernel and an elected gauge is always 1 (a lowering either
        # honored the election or emitted nothing).
        if isinstance(name, str) and name.startswith("kernel/") \
                and name.endswith("_elected"):
            kname = name[len("kernel/"):-len("_elected")]
            if kname not in _KERNEL_CHOICES:
                problems.append(
                    f"metrics.jsonl: {name} names an unregistered "
                    f"kernel (have {sorted(_KERNEL_CHOICES)})")
            elif rec.get("value") != 1:
                problems.append(
                    f"metrics.jsonl: {name} = {rec.get('value')!r} — an "
                    "elected-kernel gauge must be 1")

    # A paged serving run must carry the block-pool gauges: their
    # absence means the free-list accounting (the admission predicate's
    # ground truth) silently never ran.
    if any(r.get("kind") == "serve" and r.get("kv_layout") == "paged"
           for r in records):
        for gname in _KV_BLOCK_GAUGES:
            if gname not in gauges:
                problems.append(
                    f"metrics.jsonl: serve records declare "
                    f"kv_layout=\"paged\" but the {gname} gauge is "
                    "missing — the block-pool accounting never emitted")

    # A scale transition must come with the gauge for the trigger it
    # claims fired: the record says "queue depth crossed the line" —
    # without the autoscale/queue_depth gauge in the same run, the
    # signal behind the decision was never emitted and the transition
    # cannot be audited against it.
    for rec in records:
        if rec.get("kind") != "scale":
            continue
        gname = _SCALE_TRIGGERS.get(rec.get("trigger"))
        if gname is not None and gname not in gauges:
            problems.append(
                f"metrics.jsonl: scale record fired on "
                f"{rec['trigger']!r} but the {gname} gauge is missing "
                "— the trigger signal was never emitted")
            break

    manifest = os.path.join(run_dir, "manifest.json")
    if os.path.exists(manifest):
        try:
            with open(manifest) as f:
                m = json.load(f)
            if m.get("kind") != "manifest" or "provenance" not in m:
                problems.append("manifest.json: kind/provenance missing")
            declared = (m.get("run") or {}).get("collective_precision")
            if isinstance(declared, dict):
                # A run annotated with a precision policy must carry the
                # per-boundary gauges the lowering emits — their absence
                # means the policy was silently dropped.
                for boundary, prec in declared.items():
                    if prec in (None, "fp32"):
                        continue
                    gname = f"precision/{boundary}_bits"
                    rec = gauges.get(gname)
                    if rec is None:
                        problems.append(
                            f"manifest run.collective_precision declares "
                            f"{boundary}={prec} but metrics.jsonl has no "
                            f"{gname} gauge — the lowering dropped the "
                            "policy")
                    elif rec.get("value") != _PRECISION_BITS.get(prec):
                        problems.append(
                            f"{gname} = {rec.get('value')!r} disagrees "
                            f"with the declared {boundary}={prec} "
                            f"({_PRECISION_BITS.get(prec)} bits)")
            declared_kernel = (m.get("run") or {}).get("kernel")
            if declared_kernel:
                # A run annotated with a fused-kernel election must
                # carry the kernel/<name>_elected gauge the lowering
                # (or serving engine) emits — absence means the
                # election was silently dropped between plan and
                # program.
                names = (declared_kernel if isinstance(
                    declared_kernel, (list, tuple))
                    else [k for k, v in declared_kernel.items() if v]
                    if isinstance(declared_kernel, dict)
                    else str(declared_kernel).split(","))
                for kname in names:
                    kname = str(kname).strip()
                    if not kname:
                        continue
                    gname = f"kernel/{kname}_elected"
                    rec = gauges.get(gname)
                    if rec is None:
                        problems.append(
                            f"manifest run.kernel declares {kname!r} "
                            f"but metrics.jsonl has no {gname} gauge — "
                            "the lowering dropped the election")
                    elif rec.get("value") != 1:
                        problems.append(
                            f"{gname} = {rec.get('value')!r} disagrees "
                            f"with the declared kernel election")
        except ValueError as e:
            problems.append(f"manifest.json: invalid ({e})")

    drift = os.path.join(run_dir, "drift.json")
    if os.path.exists(drift):
        try:
            with open(drift) as f:
                d = json.load(f)
            if d.get("kind") != "drift" or not isinstance(
                    d.get("ratios"), dict):
                problems.append("drift.json: kind/ratios missing")
            # Per-level comm terms (hierarchical network model) must
            # come paired: a cross-slice time term without its byte
            # term means the cost model or the report dropped half the
            # breakdown — the dcn_gbps proposal would fit garbage.
            pred = d.get("predicted") or {}
            if pred.get("comm_time_dcn_s") and not pred.get("dcn_bytes"):
                problems.append(
                    "drift.json: predicted.comm_time_dcn_s without "
                    "predicted.dcn_bytes — per-level comm terms out "
                    "of sync")
            # Expert dispatch/combine breakout comes paired the same
            # way, and an expert-parallel run (manifest run.moe with a
            # >1 expert axis) must carry it plus the comm/a2a_bytes
            # gauge — their absence means the cost model priced the
            # MoE plan with no a2a term at all.
            if pred.get("a2a_time_s") and not pred.get("a2a_bytes"):
                problems.append(
                    "drift.json: predicted.a2a_time_s without "
                    "predicted.a2a_bytes — a2a breakout terms out "
                    "of sync")
            moe_ann = None
            if os.path.exists(manifest):
                try:
                    with open(manifest) as f:
                        moe_ann = (json.load(f).get("run") or {}).get(
                            "moe")
                except ValueError:
                    pass
            if (isinstance(moe_ann, dict)
                    and int(moe_ann.get("expert_axis", 1) or 1) > 1):
                if not pred.get("a2a_bytes"):
                    problems.append(
                        "manifest run.moe declares an expert axis > 1 "
                        "but drift.json predicted.a2a_bytes is "
                        "missing — the dispatch/combine term was "
                        "never priced")
                elif "comm/a2a_bytes" not in gauges:
                    problems.append(
                        "manifest run.moe declares an expert axis > 1 "
                        "but metrics.jsonl has no comm/a2a_bytes "
                        "gauge — the a2a breakout was never emitted")
        except ValueError as e:
            problems.append(f"drift.json: invalid ({e})")
    return problems


def _fmt(v, nd=3) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{nd}g}" if abs(v) < 1e4 else f"{v:.3e}"
    return str(v)


def _trace_sections(run_dir: str, records: list,
                    trace_filter=None) -> list:
    """The per-request trace timeline section: every trace id seen in
    the (possibly stitched) ``trace.json`` summarized with its span /
    record counts and the replicas (pids) it crossed; ``trace_filter``
    narrows to one request and expands it into the full ts-ordered
    timeline — the span tree with replica/pool attribution."""
    path = os.path.join(run_dir, "trace.json")
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            events = json.load(f)["traceEvents"]
    except (ValueError, KeyError, TypeError):
        return []
    by_trace: dict = {}
    for ev in events:
        for t in _event_trace_ids(ev):
            by_trace.setdefault(t, []).append(ev)
    if not by_trace:
        return []
    if trace_filter is not None and trace_filter not in by_trace:
        return ["## request traces", "",
                f"(trace {trace_filter!r} not found; run has "
                f"{len(by_trace)} traced request(s))", ""]
    lines = ["## request traces", "",
             "| trace | spans | records | pids | replicas |",
             "|---|---|---|---|---|"]
    wanted = [trace_filter] if trace_filter is not None \
        else sorted(by_trace)
    rec_by_trace: dict = {}
    for r in records:
        if r.get("trace_id"):
            rec_by_trace.setdefault(r["trace_id"], []).append(r)
    for t in wanted:
        evs = by_trace[t]
        spans = [e for e in evs if e.get("ph") == "X"
                 and not (e.get("args") or {}).get("folded")]
        insts = [e for e in evs if (e.get("args") or {}).get("folded")
                 or e.get("ph") == "i"]
        pids = sorted({e.get("pid") for e in evs})
        replicas = sorted(
            {str((e.get("args") or {}).get("replica"))
             for e in evs if (e.get("args") or {}).get("replica")})
        lines.append(
            f"| {t} | {len(spans)} | {len(insts)} "
            f"| {'/'.join(str(p) for p in pids)} "
            f"| {'/'.join(replicas) or '—'} |")
    lines.append("")
    if trace_filter is not None:
        lines += [f"### timeline — {trace_filter}", "",
                  "| ts (ms) | event | dur (ms) | pid | replica | "
                  "detail |",
                  "|---|---|---|---|---|---|"]
        evs = sorted(by_trace[trace_filter],
                     key=lambda e: float(e.get("ts", 0.0)))
        t0 = float(evs[0].get("ts", 0.0)) if evs else 0.0
        for ev in evs:
            args = ev.get("args") or {}
            detail = args.get("reason") or args.get("route") \
                or args.get("finish") or args.get("phase") or "—"
            dur = ev.get("dur")
            lines.append(
                f"| {_fmt((float(ev.get('ts', 0.0)) - t0) / 1e3)} "
                f"| {ev.get('name')} "
                f"| {_fmt(float(dur) / 1e3 if dur is not None else None)} "
                f"| {ev.get('pid')} "
                f"| {args.get('replica') or '—'} | {detail} |")
        lines.append("")
    return lines


def render(run_dir: str, trace_filter=None) -> str:
    """The markdown report for one flushed run directory."""
    records = load_jsonl(os.path.join(run_dir, "metrics.jsonl"))
    steps = [r for r in records if r.get("kind") == "step"]
    serves = [r for r in records if r.get("kind") == "serve"]
    dispatches = [r for r in records if r.get("kind") == "dispatch"]
    reshards = [r for r in records if r.get("kind") == "reshard"]
    faults = [r for r in records if r.get("kind") == "fault"]
    handoffs = [r for r in records if r.get("kind") == "handoff"]
    scales = [r for r in records if r.get("kind") == "scale"]
    drifts = [r for r in records if r.get("kind") == "drift"]
    counters = [r for r in records if r.get("kind") == "counter"]
    gauges = [r for r in records if r.get("kind") == "gauge"]
    hists = [r for r in records if r.get("kind") == "histogram"]

    lines = [f"# telemetry report — {run_dir}", ""]

    manifest_path = os.path.join(run_dir, "manifest.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
        prov = manifest.get("provenance", {})
        lines += ["## run", "",
                  f"- git: `{prov.get('git_sha')}`",
                  f"- jax {prov.get('jax')} / jaxlib {prov.get('jaxlib')}"
                  f" / python {prov.get('python')}"]
        run_ann = manifest.get("run", {})
        for k in sorted(run_ann):
            lines.append(f"- {k}: `{_fmt(run_ann[k])}`")
        lines.append("")

    lines += ["## steps", ""]
    if steps:
        # A fused-window record covers `steps` optimizer steps; its
        # per-step latency is duration/steps.
        per_step_ms = np.asarray([r["duration_ms"] / max(r.get("steps", 1), 1)
                                  for r in steps])
        n_steps = sum(r.get("steps", 1) for r in steps)
        # rate over FULL window durations (a fused record's examples
        # span its whole duration, not the per-step share)
        total_s = sum(r["duration_ms"] for r in steps) / 1e3
        examples = sum(r.get("examples", 0) for r in steps)
        rate = examples / total_s if total_s > 0 and examples else None
        lines += ["| records | steps | mean ms | p50 ms | p99 ms | "
                  "examples/sec |",
                  "|---|---|---|---|---|---|",
                  f"| {len(steps)} | {n_steps} "
                  f"| {_fmt(float(per_step_ms.mean()))} "
                  f"| {_fmt(float(np.percentile(per_step_ms, 50)))} "
                  f"| {_fmt(float(np.percentile(per_step_ms, 99)))} "
                  f"| {_fmt(rate)} |", ""]
    else:
        lines += ["(no per-step records)", ""]

    if serves:
        # A serving run: per-request TTFT + the fused-window-attributed
        # inter-token latencies (autodist_tpu/serving/batcher.py), with
        # the histogram instruments carrying the exact per-token
        # distributions when present.
        ttft = np.asarray([r["ttft_ms"] for r in serves], float)
        tokens = sum(int(r.get("tokens", 0)) for r in serves)
        itl = next((h for h in hists
                    if h["name"] == "serve/inter_token_ms"), None)
        rates = [r["tokens_per_sec"] for r in serves
                 if r.get("tokens_per_sec")]
        depth = next((g["value"] for g in gauges
                      if g["name"] == "serve/queue_depth"), None)
        layouts = sorted({r.get("kv_layout", "dense") for r in serves})
        lines += ["## serving", "",
                  "| requests | tokens | kv layout | ttft p50 ms | "
                  "ttft p99 ms | inter-token p50 ms | "
                  "inter-token p99 ms | tokens/s (per-request p50) | "
                  "queue depth |",
                  "|---|---|---|---|---|---|---|---|---|",
                  f"| {len(serves)} | {tokens} "
                  f"| {'/'.join(layouts)} "
                  f"| {_fmt(float(np.percentile(ttft, 50)))} "
                  f"| {_fmt(float(np.percentile(ttft, 99)))} "
                  f"| {_fmt(itl['p50'] if itl else None)} "
                  f"| {_fmt(itl['p99'] if itl else None)} "
                  f"| {_fmt(float(np.percentile(rates, 50)) if rates else None)} "
                  f"| {_fmt(depth)} |", ""]
        if "paged" in layouts:
            free = next((g["value"] for g in gauges
                         if g["name"] == "serve/kv_blocks_free"), None)
            used = next((g["value"] for g in gauges
                         if g["name"] == "serve/kv_blocks_used"), None)
            lines += [f"- kv block pool (final): {_fmt(used)} used / "
                      f"{_fmt(free)} free", ""]
        # The throughput ladder (chunked prefill / prefix caching /
        # speculative decoding): rendered whenever any request rode a
        # rung — the per-request fields are always recorded, so an
        # all-zero ladder simply stays silent.
        hit_blocks = sum(int(r.get("prefix_hit_blocks", 0))
                         for r in serves)
        proposed = sum(int(r.get("spec_proposed", 0)) for r in serves)
        accepted = sum(int(r.get("spec_accepted", 0)) for r in serves)
        chunked = [int(r.get("prefill_chunks", 1)) for r in serves
                   if int(r.get("prefill_chunks", 1)) > 1]
        if hit_blocks or proposed or chunked:
            acceptance = accepted / proposed if proposed else None
            lines += ["### throughput ladder", "",
                      "| prefix hit blocks | chunked prefills | "
                      "chunks p50 | spec proposed | spec accepted | "
                      "acceptance rate |",
                      "|---|---|---|---|---|---|",
                      f"| {hit_blocks} | {len(chunked)} "
                      f"| {_fmt(float(np.percentile(chunked, 50)) if chunked else None)} "
                      f"| {proposed} | {accepted} "
                      f"| {_fmt(acceptance)} |", ""]

    if dispatches:
        # The fleet section: routing decisions by reason, the hedge
        # win rate, and each replica's final queue depth (the
        # fleet/<name>/queue_depth gauges the router emits per round).
        by_reason = {}
        for r in dispatches:
            by_reason[r.get("reason")] = by_reason.get(r.get("reason"),
                                                       0) + 1
        counter_vals = {r["name"]: r["value"] for r in counters}
        hedges = counter_vals.get("fleet/hedges", 0)
        hedge_wins = counter_vals.get("fleet/hedge_wins", 0)
        win_rate = hedge_wins / hedges if hedges else None
        lines += ["## fleet", "",
                  "| dispatches | route | failover | hedge | drain | "
                  "hedge win rate | replacements |",
                  "|---|---|---|---|---|---|---|",
                  f"| {len(dispatches)} "
                  f"| {by_reason.get('route', 0)} "
                  f"| {by_reason.get('failover', 0)} "
                  f"| {by_reason.get('hedge', 0)} "
                  f"| {by_reason.get('drain', 0)} "
                  f"| {_fmt(win_rate)} "
                  f"| {_fmt(counter_vals.get('fleet/replacements'))} |",
                  ""]
        depth = {g["name"]: g["value"] for g in gauges
                 if g["name"].startswith("fleet/")
                 and g["name"].endswith("/queue_depth")}
        if depth:
            lines += ["| replica | queue depth (final) |", "|---|---|"]
            for name in sorted(depth):
                replica = name[len("fleet/"):-len("/queue_depth")]
                lines.append(f"| {replica} | {_fmt(depth[name])} |")
            lines.append("")

    if handoffs:
        # The disaggregation section: one KV-prefix handoff per request
        # that crossed the prefill→decode boundary, summarized by route
        # plus the prefill→decode pairings — the same pairing --check
        # gates on.
        blocks = sum(int(r.get("blocks", 0)) for r in handoffs)
        moved = sum(int(r.get("bytes_moved", 0)) for r in handoffs)
        durs = np.asarray([r["duration_ms"] for r in handoffs
                           if r.get("duration_ms") is not None], float)
        routes = "/".join(sorted({str(r.get("route")) for r in handoffs}))
        lines += ["## disaggregated serving", "",
                  "| handoffs | route | blocks | MB moved | p50 ms | "
                  "p99 ms |",
                  "|---|---|---|---|---|---|",
                  f"| {len(handoffs)} | {routes} | {blocks} "
                  f"| {_fmt(moved / 1e6)} "
                  f"| {_fmt(float(np.percentile(durs, 50)) if len(durs) else None)} "
                  f"| {_fmt(float(np.percentile(durs, 99)) if len(durs) else None)} |",
                  ""]
        pairs = {}
        for r in handoffs:
            key = (r.get("prefill_replica"), r.get("decode_replica"))
            pairs[key] = pairs.get(key, 0) + 1
        lines += ["| prefill → decode | handoffs |", "|---|---|"]
        for (src, dst) in sorted(pairs):
            lines.append(f"| {src} → {dst} | {pairs[(src, dst)]} |")
        lines.append("")

    if scales:
        # The autoscaling section: every grow/shrink transition with
        # the trigger that fired it and the measured value against its
        # threshold, in record order.
        lines += ["## autoscaling", "",
                  "| direction | trigger | value | threshold | "
                  "replicas | replica |",
                  "|---|---|---|---|---|---|"]
        for r in scales:
            lines.append(
                f"| {r.get('direction')} | {r.get('trigger')} "
                f"| {_fmt(r.get('value'))} | {_fmt(r.get('threshold'))} "
                f"| {r.get('replicas_before')} → "
                f"{r.get('replicas_after')} "
                f"| {r.get('replica', '—')} |")
        lines.append("")
        final = {g["name"]: g["value"] for g in gauges
                 if g["name"] in ("autoscale/queue_depth",
                                  "autoscale/ttft_p99_ms")}
        if final:
            lines.append(
                f"- trigger gauges (final): queue depth "
                f"{_fmt(final.get('autoscale/queue_depth'))}, "
                f"ttft p99 {_fmt(final.get('autoscale/ttft_p99_ms'))} ms")
            lines.append("")

    if drifts:
        # The ONLINE drift monitor's breach records (edge-triggered:
        # one row per crossing, in either direction) — the live
        # sibling of the post-hoc drift.json section below.
        lines += ["## online drift breaches", "",
                  "| step | term | ratio | band | direction |",
                  "|---|---|---|---|---|"]
        for r in drifts:
            lines.append(
                f"| {r.get('step')} | {r.get('term')} "
                f"| {_fmt(r.get('ratio'))} "
                f"| ±{_fmt(r.get('threshold'))} "
                f"| {r.get('direction')} |")
        lines.append("")

    lines += _trace_sections(run_dir, records, trace_filter)

    if reshards:
        lines += ["## reshards", "",
                  "| route | leaves | MB moved | peak host MB | ms |",
                  "|---|---|---|---|---|"]
        for r in reshards:
            lines.append(
                f"| {r['route']} | {r['leaves']} "
                f"| {_fmt(r['bytes_moved'] / 1e6)} "
                f"| {_fmt(r['peak_host_bytes'] / 1e6)} "
                f"| {_fmt(r['duration_ms'])} |")
        lines.append("")

    if faults:
        # One row per injection, joined with its terminal outcome (the
        # same pairing --check gates on); standalone detections ride
        # the notes column of their injection when present.
        lines += ["## faults", "",
                  "| fault | target | phase(s) | outcome | step/t |",
                  "|---|---|---|---|---|"]
        injections = [r for r in faults if r.get("phase") == "injected"]
        for inj in injections:
            related = [r for r in faults if r is not inj
                       and r.get("fault") == inj.get("fault")
                       and r.get("target") == inj.get("target")]
            phases = " → ".join(["injected"]
                                + [r.get("phase", "?") for r in related])
            outcome = next((r.get("action") or r.get("phase")
                            for r in reversed(related)
                            if r.get("phase") in _FAULT_TERMINAL), "NONE")
            when = inj.get("step")
            when = f"step {when}" if when is not None \
                else f"t={_fmt(inj.get('t_s'))}s"
            lines.append(f"| {inj.get('fault')} | {inj.get('target')} "
                         f"| {phases} | {outcome} | {when} |")
        orphans = [r for r in faults if r.get("phase") != "injected"
                   and not any(i.get("fault") == r.get("fault")
                               and i.get("target") == r.get("target")
                               for i in injections)]
        for r in orphans:   # real (un-injected) faults the run survived
            lines.append(f"| {r.get('fault')} | {r.get('target')} "
                         f"| {r.get('phase')} | {r.get('action') or '—'} "
                         f"| step {_fmt(r.get('step'))} |")
        lines.append("")

    if counters or gauges:
        lines += ["## counters / gauges", "", "| name | value |", "|---|---|"]
        for r in counters + gauges:
            lines.append(f"| {r['name']} | {_fmt(r['value'])} |")
        lines.append("")
    if hists:
        lines += ["## histograms", "",
                  "| name | n | mean | p50 | p99 |", "|---|---|---|---|---|"]
        for r in hists:
            lines.append(f"| {r['name']} | {r['count']} | {_fmt(r['mean'])} "
                         f"| {_fmt(r['p50'])} | {_fmt(r['p99'])} |")
        lines.append("")

    drift_path = os.path.join(run_dir, "drift.json")
    if os.path.exists(drift_path):
        with open(drift_path) as f:
            drift = json.load(f)
        lines += ["## drift (measured / predicted)", "",
                  "| term | ratio |", "|---|---|"]
        for k, v in sorted(drift.get("ratios", {}).items()):
            lines.append(f"| {k} | {_fmt(v)} |")
        mfu = drift.get("measured", {}).get("mfu")
        if mfu is not None:
            lines.append(f"| mfu (measured) | {_fmt(mfu)} |")
        lines.append("")
        proposal = drift.get("proposal")
        if proposal:
            link = {k: v for k, v in proposal.items() if k != "note"}
            lines += [f"calibration proposal: `{json.dumps(link)}`",
                      f"({proposal.get('note')})", ""]
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", help="directory a telemetry run flushed "
                                    "(contains metrics.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="validate the artifact schema; non-zero exit on "
                         "a break (CI smoke)")
    ap.add_argument("--trace", default=None, metavar="ID",
                    help="expand one request's distributed trace into "
                         "its full timeline (span tree with replica "
                         "attribution)")
    args = ap.parse_args(argv)
    if args.check:
        problems = check_schema(args.run_dir)
        if problems:
            for p in problems:
                print(f"SCHEMA: {p}", file=sys.stderr)
            return 2
        print(f"schema OK: {args.run_dir}")
        return 0
    try:
        print(render(args.run_dir, trace_filter=args.trace))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
